"""The always-on extraction daemon: ingest queue → tenant scheduler → packer.

Turns the batch pipeline into a serving loop (ROADMAP item 1): one
:class:`..extractors.base.PackedSession` lives for the daemon's lifetime, so
the corpus packer's slot queues stay warm ACROSS requests — the tail batch
of tenant A's request packs with the head of tenant B's — and the mesh never
drains while there is backlog. The Podracer split (PAPERS.md) is preserved:
CPU-bound decode producers (the byte-capped ``DecodePrefetcher``) are the
buffer that absorbs bursts, the device consumer runs one batch always in
flight per bucket, and the scheduler in between decides *whose* video feeds
the queues next (weighted-fair + deadline, :mod:`.scheduler`).

Lifecycle:

- **drain** (SIGTERM / SIGINT / ``{"op": "drain"}``): stop admitting, finish
  every admitted video, pad-flush the partial queues, resolve all writes,
  write every request's result record, exit 0/1.
- **reload** (SIGHUP / ``{"op": "reload"}``): re-read ``tenants.json`` from
  the spool directory (weights/quotas) and close all tenant breakers.
- a second SIGTERM/SIGINT aborts immediately (KeyboardInterrupt semantics;
  the write-before-done and atomic-write invariants still hold on unwind).

Failure semantics: a video failure is attempted once per schedule; transient
classes re-enter the queue (same admission seq — retries do not go to the
back of the line) until ``--retries`` is spent, then fail terminally into
the shared failure manifest AND the owning request's result record. Terminal
failures count against the tenant's breaker (``--tenant_max_failures``):
tripping fails that tenant's queued videos fast and rejects its new
submissions until a reload, while other tenants keep completing.

With ``--cache_dir`` (docs/caching.md) every popped job consults the
content-addressed feature cache first — a hit writes outputs + manifests
with zero decode and zero device steps — and identical MISSES coalesce
in flight (:class:`..cache.InflightCoalescer`): N tenants submitting the
same bytes run ONE extraction, waiters replay from the fresh entry with
quota/fairness charged per waiter, and a leader failure re-enqueues the
waiters (next replay leads on its own retry budget) instead of charging a
neighbour's fault to their breakers.

Observability (docs/observability.md): the daemon always keeps a metrics
registry — queue-wait / end-to-end latency / decode / transfer histograms
labeled tenant × model, per-bucket occupancy gauges, stage counters mirrored
from the service clock — served by the ``stats`` (p50/p99 summaries,
``"schema": 1``) and ``metrics`` (full snapshot + Prometheus text) socket
ops. With ``--telemetry_dir`` every request and video additionally gets a
journaled lifecycle (admitted → queued → popped → decode → dispatch →
device → done/failed, plus cache/coalesce/stale-flush/autoscale/breaker
events) exportable as a Chrome/Perfetto trace; ``healthz`` reports liveness
+ staleness from the API thread, and the ``profile`` op drives an on-demand
``jax.profiler`` session in the live daemon.

With ``--serve_models`` (ROADMAP item 2) several feature types co-reside on
ONE mesh: requests pick a model via their ``feature_type`` key (admission
validates it against the loaded set and rejects unknown models with a clean
record), each model's extractor is constructed lazily on first traffic
sharing the primary's run resources (:class:`..extractors.base.
MultiModelSessions`), and the packer interleaves dispatch round-robin across
models — mixed traffic never drains the device while ANY model has backlog.
Tenant fairness and deadlines stay GLOBAL across models (a tenant cannot
dodge its weight by spreading over models), breakers stay per tenant across
models, and a graceful drain finishes every admitted model's in-flight
batches.
"""

from __future__ import annotations

import json
import os
import signal
import sys
import threading
import time
from typing import Dict, Optional

from ..cache import InflightCoalescer
from ..config import resolve_model_defaults
from ..extractors.base import MultiModelSessions, derive_model_config
from ..io.output import (
    feature_output_dir,
    load_done_set,
    request_result_path,
    write_request_result,
)
from ..obs import MetricsRegistry
from ..reliability import (
    DeviceError,
    TenantBreaker,
    TenantBreakerOpen,
    classify,
    record_failure,
)
from ..reliability.faults import fault_point
from ..utils.metrics import StageClock
from .autoscale import DecodeAutoscaler
from .ingest import SPOOL_TENANTS_FILE, SocketAPI, SpoolWatcher, accepted_path
from .request import RequestRejected, ServiceRequest, VideoJob, parse_request
from .scheduler import RequestQueue
from .wal import WAL_NAME, AdmissionLog

# healthz `stale` threshold default (--healthz_stale_sec): the serving loop
# stamps every step (idle steps included, ~poll_interval apart), so an age
# past this means the daemon thread is stuck — wedged, or in a legitimately
# long first-traffic compile
HEALTH_STALE_SEC = 10.0


class ExtractionService:
    """One extractor serving a live request stream until drained."""

    def __init__(self, extractor, poll_interval: float = 0.05,
                 factory=None):
        cfg = extractor.cfg
        self.ex = extractor
        self.cfg = cfg
        spec = extractor.pack_spec()
        if spec is None:
            raise ValueError(
                f"--serve needs a packing path, but {cfg.feature_type} has "
                "none under this config (--show_pred and the single-clip "
                "frame-sharded flow sandwich are batch-only)")
        self.spec = spec
        # co-resident model set (--serve_models): the primary first (the
        # default for requests without a feature_type), extras deduped in
        # flag order. Each extra's DERIVED config (its own reference
        # stack/step defaults) must validate NOW — a daemon that would die
        # constructing model B on its first request should die at startup
        extras = []
        for m in cfg.serve_models or ():
            if m != cfg.feature_type and m not in extras:
                extras.append(m)
        self.models = (cfg.feature_type, *extras)
        for m in extras:
            resolve_model_defaults(derive_model_config(cfg, m)).validate()
        self._poll = poll_interval
        # telemetry (docs/observability.md): _open_run_resources opens the
        # span journal (--telemetry_dir, may be None) and the metrics
        # registry (always on under --serve — `stats`/`metrics` ops need it);
        # the service clock runs for the daemon's lifetime and MIRRORS its
        # per-stage seconds/bytes into the registry, so decode/device/
        # transfer attribution feeds the autoscaler, the stats op, and the
        # Prometheus exposition from one accumulator
        extractor._open_run_resources()
        self.journal = extractor._journal
        if extractor._metrics is None:  # a directly-constructed service
            extractor._metrics = MetricsRegistry()
        self.metrics = extractor._metrics
        extractor.clock = StageClock(registry=self.metrics)
        # ``factory(model) -> Extractor`` overrides lazy co-model
        # construction (tests wire toy models); the default builds the real
        # extractor for the derived config, sharing the primary's mesh
        self.sessions = MultiModelSessions(
            extractor, self.models, on_done=self._video_done,
            on_failed=self._video_failed, factory=factory,
            primary_spec=spec)
        self.session = self.sessions
        self.packer = self.sessions.packer
        # the queue owns the queue-wait signal: it emits queued/popped
        # journal events and feeds the queue_wait_seconds histogram + the
        # per-tenant depth gauges (serve/scheduler.py)
        self.queue = RequestQueue(default_quota=cfg.tenant_quota,
                                  journal=self.journal,
                                  metrics=self.metrics)
        self.breaker = TenantBreaker(cfg.tenant_max_failures)
        self.notify_dir = cfg.notify_dir or os.path.join(
            cfg.spool_dir or cfg.output_path, "results")
        # write-ahead admission log (serve/wal.py): every accepted request
        # is on disk before its submit is acknowledged, so a crashed daemon's
        # admitted-but-unfinished requests replay at the next startup
        # (recover()). Default location: beside the spool it serves.
        wal_file = cfg.wal_path
        if wal_file is None and cfg.spool_dir:
            wal_file = os.path.join(cfg.spool_dir, WAL_NAME)
        self._wal = (AdmissionLog(wal_file, fsync_sec=cfg.wal_fsync_sec,
                                  journal=self.journal, metrics=self.metrics)
                     if wal_file and wal_file.lower() != "none" else None)
        self._autoscaler = (DecodeAutoscaler()
                            if cfg.decode_workers == 0 else None)
        self._as_snapshot = (time.perf_counter(), 0.0, 0, 0)
        # --resume strips already-done videos at admission, per model (each
        # feature type keeps its own output subtree and done-manifest)
        self._done_sets: Dict[str, frozenset] = {}
        self._lock = threading.RLock()
        self._requests: Dict[str, ServiceRequest] = {}
        self._jobs: Dict[str, object] = {}  # abspath -> in-flight VideoJob
        # completed requests whose result record is still being written
        # (the write runs OUTSIDE the service lock): status() answers from
        # here during the window, and submit() still rejects the id as live
        self._publishing: Dict[str, dict] = {}
        # in-flight dedup (--cache_dir): identical (content, fingerprint)
        # misses run one extraction; touched only on the daemon thread
        self._coalescer = InflightCoalescer()
        self._draining = threading.Event()
        self._hup = threading.Event()
        # hung-step watchdog (--step_watchdog_sec): the monitor thread SETS
        # this when the loop has not stepped past the threshold; the daemon
        # thread clears it at its next step and fails the stalled batch
        # transiently (Events only — no unguarded cross-thread stores)
        self._stalled = threading.Event()
        self._watchdog_stop = threading.Event()
        self._idle_since: Optional[float] = None
        self._completed_requests = 0
        # healthz liveness: the loop stamps _last_step every step(); the
        # socket's healthz op reports the age so a wedged daemon thread is
        # visible from the (still-responsive) API thread. An on-demand
        # jax.profiler session (`profile` op) is tracked by its trace dir.
        self._started = time.monotonic()
        self._last_step = self._started
        self._profiling: Optional[str] = None
        # terminal failures with no extractor to account them (a co-loaded
        # model whose lazy construction failed) — the exit code includes them
        self._service_failures = 0
        self._closed = False
        if cfg.spool_dir:
            self._load_tenants_config(initial=True)

    def _emit(self, event: str, **fields) -> None:
        """One journal event (no-op without --telemetry_dir; never blocks)."""
        if self.journal is not None:
            self.journal.emit(event, **fields)

    # --- submission (ingest threads + tests call these) ----------------------

    def submit(self, payload, request_id: Optional[str] = None,
               source: str = "api") -> ServiceRequest:
        """Admit one request end to end; raises :class:`RequestRejected`."""
        if self._draining.is_set():
            raise RequestRejected("service is draining; resubmit after "
                                  "restart")
        request = parse_request(payload, request_id=request_id, source=source)
        # resolve the model at admission: the daemon's default when omitted,
        # and ANY named model must be in the loaded set — an unknown model is
        # a clean synchronous rejection (record written where the submitter
        # looks), never a daemon crash or a silent terminal failure
        ft = request.feature_type or self.cfg.feature_type
        if ft not in self.models:
            raise RequestRejected(
                f"feature_type {ft!r} is not loaded (serving: "
                f"{', '.join(self.models)}); start the daemon with "
                "--serve_models to co-load it")
        request.feature_type = ft
        # the resume manifest read is disk I/O — do it BEFORE taking the
        # service lock; submitters on other ingest threads and the serving
        # loop's pop all convoy on this lock (no blocking work under it)
        done = self._resume_done(ft)
        to_queue = request.videos
        resumed = ()
        if done:
            resumed = tuple(v for v in request.videos
                            if os.path.abspath(v) in done)
            to_queue = tuple(v for v in request.videos
                             if os.path.abspath(v) not in done)
        with self._lock:
            if (request.request_id in self._requests
                    or request.request_id in self._publishing):
                raise RequestRejected(
                    f"request_id {request.request_id!r} is already live")
            if self.breaker.tripped(request.tenant):
                raise RequestRejected(
                    f"tenant {request.tenant!r} breaker is open "
                    f"({self.breaker.failures(request.tenant)} terminal "
                    "failures); fix the inputs and SIGHUP-reload")
            # the scheduler rejects duplicates against its QUEUED set; a
            # path that was already popped (ingested, rows/writes pending)
            # is only visible here — without this check a resubmission
            # (same or another model) would overwrite _jobs[path] and
            # packer.begin() would discard the first attempt's in-flight
            # assembly, silently losing the original request's video
            inflight = [v for v in to_queue
                        if os.path.abspath(v) in self._jobs]
            if inflight:
                raise RequestRejected(
                    f"video(s) currently in flight under a live request: "
                    f"{', '.join(sorted(inflight)[:3])}"
                    + ("…" if len(inflight) > 3 else ""))
            # hold=True when the WAL is on: the jobs get their admission
            # seqs and reserve quota/duplicate slots, but stay invisible to
            # the serving loop until the admission record is durable — a
            # pop-dispatch-crash before the append lands would lose the
            # request (the spool claim is already consumed by then)
            jobs = (self.queue.submit(request, videos=to_queue,
                                      hold=self._wal is not None)
                    if to_queue else [])
            # mark BEFORE releasing the lock: _publish_result (daemon
            # thread) checks this flag to resolve the WAL entry, and an
            # early resolve must find the flag already set (the log itself
            # annihilates a resolve-before-append race)
            request.wal_logged = self._wal is not None and bool(jobs)
            # after queue.submit: a quota rejection there must not leave an
            # admitted event for a request that was never admitted (the
            # per-video queued events landing µs earlier is harmless — the
            # exporter anchors the request span on THIS event)
            self._emit("request_admitted", request=request.request_id,
                       tenant=request.tenant, model=ft,
                       videos=len(request.videos), queued=len(to_queue),
                       resumed=len(resumed))
            self._requests[request.request_id] = request
            for v in resumed:
                request.done.append(os.path.abspath(v))
            finished = self._finish_request_locked(request)
        # the ack barrier (docs/serving.md "Crash recovery"): the admitted
        # record — id, tenant, paths, model, deadline, admission seqs — is
        # durably appended BEFORE this submit returns/acknowledges. Disk
        # I/O, so outside the service lock like every other write.
        if request.wal_logged:
            self._wal.append_admitted({
                "request": request.request_id, "tenant": request.tenant,
                "feature_type": ft, "deadline": request.deadline,
                "source": source, "videos": [j.path for j in jobs],
                "seqs": [j.seq for j in jobs], "wall": time.time(),
            })
            # record durable (or the log degraded loudly): NOW the jobs may
            # feed the serving loop
            self.queue.release(jobs)
        # result record + prints are blocking work: outside the lock
        print(f"[serve] accepted {request.request_id} "
              f"(tenant={request.tenant}, {len(to_queue)} queued"
              + (f", {len(resumed)} resumed" if resumed else "") + ")")
        self._publish_result(finished)
        return request

    def _resume_done(self, feature_type: str) -> frozenset:
        """The model's done-manifest set (empty without --resume). The memo
        is service-lock-guarded; the manifest READ runs off-lock (disk I/O
        never happens under the service lock), and a lost race between two
        first submitters just loads the same set twice."""
        if not self.cfg.resume:
            return frozenset()
        with self._lock:
            done = self._done_sets.get(feature_type)
        if done is None:
            loaded = frozenset(load_done_set(feature_output_dir(
                self.cfg.output_path, feature_type)))
            with self._lock:
                done = self._done_sets.setdefault(feature_type, loaded)
        return done

    def recover(self) -> int:
        """Replay a crashed predecessor's unresolved WAL admissions
        (``--recover``, serve/wal.py; docs/serving.md "Crash recovery").

        Runs at startup BEFORE the ingest transports: each unresolved entry
        is deduped against its already-published result record and the
        per-model done-manifests (``--resume`` semantics — recovery always
        dedupes, whatever ``--resume`` says: exactly-once needs it), then
        the survivors re-enter the scheduler with their ORIGINAL admission
        seqs and deadlines through the requeue machinery, so a recovered
        video never goes to the back of the line behind post-restart
        traffic. Returns how many requests were re-admitted.
        """
        if self._wal is None:
            return 0
        entries = self._wal.replayable()
        if not entries:
            return 0
        if not self.cfg.recover:
            print(f"[serve] --recover off: dropping {len(entries)} "
                  "unresolved WAL admission(s) from a previous daemon",
                  file=sys.stderr)
            for rec in entries:
                self._wal.resolve(rec["request"], "failed")
            return 0
        self._emit("recovery_started", entries=len(entries),
                   corrupt=self._wal.corrupt_lines or None)
        print(f"[serve] recovery: {len(entries)} unresolved admission(s) "
              f"in {self._wal.path}"
              + (f" ({self._wal.corrupt_lines} torn/corrupt line(s) "
                 "tolerated)" if self._wal.corrupt_lines else ""))
        # new admissions must never collide with a replayed seq (the tenant
        # heaps tiebreak on seq), and replays should keep their priority
        self.queue.advance_seq(self._wal.max_seq())
        replayed = 0
        for rec in entries:
            rid = rec["request"]
            ft = rec.get("feature_type") or self.cfg.feature_type
            if os.path.exists(request_result_path(self.notify_dir, rid)):
                # the crash hit between publish and resolve: the submitter
                # already has its answer
                self._wal.resolve(rid, "done")
                self._emit("recovery_skipped_duplicate", request=rid,
                           reason="result record exists")
                print(f"[serve] recovery: {rid} already published; skipped")
                continue
            if ft not in self.models:
                print(f"[serve] recovery: {rid} wants model {ft!r} which "
                      "this daemon no longer loads; dropping the entry",
                      file=sys.stderr)
                self._wal.resolve(rid, "failed")
                continue
            request = ServiceRequest(
                rid, rec.get("tenant") or "default",
                tuple(rec.get("videos") or ()),
                deadline=rec.get("deadline"),
                source=rec.get("source") or "recovery", feature_type=ft)
            request.wal_logged = True
            done = frozenset(load_done_set(feature_output_dir(
                self.cfg.output_path, ft)))
            seqs = rec.get("seqs") or []
            jobs = []
            with self._lock:
                self._requests[rid] = request
                for i, path in enumerate(request.videos):
                    if path in done:
                        request.done.append(path)  # landed pre-crash
                        continue
                    seq = seqs[i] if i < len(seqs) else 0
                    jobs.append(VideoJob(path, request, seq=seq))
                finished = (self._finish_request_locked(request)
                            if not jobs else None)
            if jobs:
                # original seqs + deadlines, through the same requeue path
                # a transient retry takes (video_requeued journal events)
                self.queue.requeue_all(jobs)
            replayed += 1
            self.metrics.inc("recovery_replayed_total")
            self._emit("recovery_replayed", request=rid,
                       tenant=request.tenant, model=ft, videos=len(jobs),
                       resumed=len(request.done))
            print(f"[serve] recovery: re-admitted {rid} "
                  f"({len(jobs)} video(s) to run, {len(request.done)} "
                  "already done)")
            # every video already landed: publish now (resolves the entry)
            self._publish_result(finished)
        return replayed

    def reject(self, request_id: str, reason: str, source: str = "api",
               payload=None) -> None:
        """Record a rejected submission where the submitter will look."""
        tenant = (payload or {}).get("tenant") if isinstance(payload, dict) \
            else None
        print(f"[serve] rejected {request_id}: {reason}")
        self._emit("request_rejected", request=request_id, tenant=tenant,
                   reason=reason[:200])
        try:
            write_request_result(self.notify_dir, request_id, {
                "request_id": request_id,
                "tenant": tenant if isinstance(tenant, str) else None,
                "state": "rejected",
                "reason": reason,
                "source": source,
                "completed_at": time.time(),
            })
        except Exception as e:  # noqa: BLE001 — fault-barrier: a rejection record is best-effort; the daemon must outlive a full notify disk
            print(f"[serve] could not record rejection {request_id}: {e}",
                  file=sys.stderr)

    # --- the serving loop (daemon thread only) -------------------------------

    def step(self) -> bool:
        """One scheduling step; True when it did video work."""
        self._last_step = time.monotonic()  # healthz liveness stamp
        if self._stalled.is_set():
            # the watchdog flagged a stall while the previous step was
            # wedged (hung device dispatch, stuck decode): now that the
            # loop is stepping again, fail the stalled batch transiently —
            # its victims requeue through the same slot-attribution path
            # as any co-packed batch failure
            self._stalled.clear()
            self._requeue_stalled()
        if self._hup.is_set():
            self._hup.clear()
            self.reload()
        with self._lock:
            # pop + register atomically: between leaving the scheduler's
            # queued set and appearing in _jobs, a resubmission of the same
            # path would pass BOTH duplicate checks (service lock → queue
            # lock here matches the submit path's ordering)
            job = self.queue.next_job()
            if job is not None:
                self._jobs[job.path] = job
        if job is None:
            # resolve outstanding writes so finished videos complete their
            # requests even while no new work arrives
            self.session.emit_completed(reap_limit=0)
            if self.packer.has_pending():
                now = time.perf_counter()
                if self._idle_since is None:
                    self._idle_since = now
                if (self._draining.is_set()
                        or now - self._idle_since >= self.cfg.idle_flush_sec):
                    # nothing left to pack with: latency beats occupancy —
                    # pad-flush the partial queues so in-flight requests
                    # complete now instead of at the next burst
                    self.session.drain(final=False)
                    self._idle_since = None
            return False
        self._idle_since = None
        path = job.path
        model = job.feature_type or self.cfg.feature_type
        tenant = job.request.tenant
        if self.breaker.tripped(tenant):
            # raced a trip while queued (requeue after drain_tenant)
            self._fail_job_fast(job, "breaker opened while queued")
            return True
        try:
            # first traffic for a co-loaded model constructs its extractor
            # here, on the daemon thread, sharing the primary's resources
            ex = self.sessions.extractor(model)
        except KeyboardInterrupt:
            raise
        except Exception as e:  # noqa: BLE001 — fault-barrier: a model whose lazy construction fails (missing weights, invalid derived config) must fail ITS job cleanly, not kill the daemon serving the other models
            if not self._video_failed(path, e):
                # terminal: no session exists to run the shared accounting,
                # so record + count + journal here (the exit code must stay
                # honest AND the journal's video_failed stream must agree
                # with the failure counter — ex._fail, the usual emitter,
                # never runs when no extractor exists)
                print(f"[serve] cannot construct model {model!r} for "
                      f"{path}: {e}", file=sys.stderr)
                self._service_failures += 1
                err_class, transient = classify(e)
                self._emit("video_failed", video=path, model=model,
                           error_class=err_class, transient=transient)
                self.metrics.inc("videos_failed_total", model=model,
                                 error_class=err_class)
                try:
                    record_failure(feature_output_dir(
                        self.cfg.output_path, model), path, e)
                except OSError as rec_err:
                    print(f"warning: could not record failure for {path}: "
                          f"{rec_err}", file=sys.stderr)
            return True
        if self._try_cache(job, ex):
            return True
        # decode hints route per model and must not gate on the CURRENT
        # job's pool: a popped non-frame-stream job (vggish) still hints
        # queued frame-stream jobs of co-resident models (schedule_decode
        # no-ops for models without a frame stream)
        self.sessions.schedule_decode(path, model)
        pool = self.sessions.decode_pool
        if pool is not None:
            for j in self.queue.peek_jobs(max(pool.workers - 1, 0)):
                self.sessions.schedule_decode(
                    j.path, j.feature_type or self.cfg.feature_type)
        # per-video decode/transfer histograms: ingest pulls the clip stream
        # synchronously on this thread, so the service clock's stage deltas
        # over the ingest window are this video's attribution (approximate
        # by construction — concurrent staging-ring commits land in whatever
        # window is open — but the distribution is what capacity questions
        # need, not per-video forensics)
        clock = self.ex.clock
        d0 = clock.seconds.get("decode", 0.0)
        x0 = clock.seconds.get("transfer", 0.0)
        try:
            self.session.ingest(path, model, retries=0)
        except KeyboardInterrupt:
            raise
        except Exception as e:  # noqa: BLE001 — fault-barrier: the per-video isolation point (serving loop)
            # one schedule = one attempt; _video_failed (the session's
            # on_failed hook) owns the requeue-vs-terminal decision so this
            # path, failed writes, and co-packed batch victims all share one
            # retry budget
            self.session.fail(path, model, e)
        finally:
            self.sessions.release_decode(path)
            self.metrics.observe(
                "decode_seconds",
                max(clock.seconds.get("decode", 0.0) - d0, 0.0),
                tenant=tenant, model=model)
            self.metrics.observe(
                "transfer_seconds",
                max(clock.seconds.get("transfer", 0.0) - x0, 0.0),
                tenant=tenant, model=model)
        self.session.emit_completed(reap_limit=1)
        return True

    def run(self) -> int:
        """Serve until drained; returns 0 (no terminal failures) or 1."""
        if self.cfg.step_watchdog_sec:
            threading.Thread(target=self._watchdog_loop, daemon=True,
                             name="step-watchdog").start()
        try:
            while True:
                did = self.step()
                if self._draining.is_set() and self._quiescent():
                    # everything admitted has been ingested; pad-flush what
                    # still sits in the queues and resolve every write. A
                    # failed flush/write may REQUEUE its transient victims —
                    # quiescent again only once they resolved too
                    self.session.drain(final=True)
                    if self._quiescent():
                        break
                if not did:
                    time.sleep(self._poll)
            with self._lock:
                pending = [self._finish_request_locked(request, force=True)
                           for request in list(self._requests.values())]
            for finished in pending:
                self._publish_result(finished)
        finally:
            self.close()
        return (0 if self.sessions.failures == 0
                and self._service_failures == 0 else 1)

    def request_drain(self) -> None:
        if not self._draining.is_set():
            print("[serve] drain requested: finishing admitted videos, then "
                  "exiting")
        self._draining.set()

    def reload(self) -> None:
        """SIGHUP: re-read tenants.json, close every tenant breaker."""
        if self.cfg.spool_dir:
            self._load_tenants_config()
        self.breaker.reset()
        print("[serve] reload: tenant config re-read, breakers closed")

    def close(self) -> None:
        """Tear down run resources (idempotent; run() calls it on exit)."""
        if self._closed:
            return
        self._closed = True
        self._watchdog_stop.set()
        if self._wal is not None:
            self._wal.close()
        self.sessions.close()
        self.ex.clock = None

    def _try_cache(self, job, ex) -> bool:
        """Feature-cache consult + in-flight coalescing for one popped job.

        ``ex`` is the job's MODEL extractor (multi-model daemons route every
        consult, publish, and key memo through the owning model — its config
        fingerprint keys the entry, so models never collide in the shared
        store). True when no extraction should run this step: the job was
        served from the cache (outputs + manifests written, zero device
        steps) or parked behind an identical in-flight extraction. Fairness
        holds either way — the pop that got us here already advanced the
        tenant's virtual time, and a parked waiter's replay is another pop.
        """
        if ex._cache is None:
            return False
        path = job.path
        model = job.feature_type or self.cfg.feature_type
        feats = ex._cache_fetch(path)
        if feats is not None:
            self.sessions.release_decode(path)  # may have been hint-scheduled
            job.from_cache = True
            try:
                ex._publish_cache_hit(path, feats,
                                      on_done=self._video_done)
            except KeyboardInterrupt:
                raise
            except Exception as e:  # noqa: BLE001 — fault-barrier: a hit's write failure is this video's own failure, owned by the shared requeue-vs-terminal logic
                self.session.fail(path, model, e)
                return True
            self.session.emit_completed(reap_limit=1)
            return True
        key = ex._cache_keys.get(os.path.abspath(path))
        if key is None:
            return False  # unhashable content: extract without coalescing
        if self._coalescer.wait(key, job):
            # identical extraction already in flight: park this job — the
            # leader's completion (or failure) re-enqueues it
            self.sessions.release_decode(path)
            self._emit("coalesced", video=path,
                       request=job.request.request_id,
                       tenant=job.request.tenant, model=model)
            return True
        self._coalescer.lead(key, path)
        return False

    # --- bookkeeping (PackedSession callbacks; daemon thread) ----------------

    def _release_waiters_locked(self, path: str) -> None:
        """Leader ``path`` resolved: re-enqueue its coalesced waiters with
        their original admission seqs (replays do not go to the back). After
        a successful leader they replay as cache hits; after a failed one the
        first replay becomes the next leader on its OWN retry budget — a
        leader's fault never reaches a waiter tenant's breaker."""
        waiters = self._coalescer.finish(path)
        if not waiters:
            return
        for wjob in waiters:
            self._jobs.pop(wjob.path, None)
        self.queue.requeue_all(waiters)

    def _video_done(self, path: str) -> None:
        with self._lock:
            self._release_waiters_locked(path)
            job = self._jobs.pop(path, None)
            if job is None:
                return
            if job.from_cache:
                job.request.cache_hits += 1
            # end-to-end latency: admission → outputs landed (requeues and
            # write resolution included) — the per-tenant/per-model p50/p99
            # the stats op reports and the journal's queued→done chain pins
            self.metrics.observe(
                "e2e_latency_seconds",
                max(time.monotonic() - job.admitted_at, 0.0),
                tenant=job.request.tenant,
                model=job.feature_type or self.cfg.feature_type)
            job.request.done.append(path)
            finished = self._finish_request_locked(job.request)
        self._publish_result(finished)

    def _video_failed(self, path: str, exc: BaseException) -> bool:
        """Claim a transient failure by re-enqueueing (returns True — the
        shared terminal accounting is skipped), else record it terminally.

        This is where a co-packed batch failure's VICTIMS land (a device
        fault on one dispatched batch fails every co-resident video): they
        are transient by classification, so they re-enter the scheduler
        under the same retry budget as a directly-failing video — an
        innocent tenant's video lost to a neighbour's poisoned batch must
        not count against that tenant's breaker."""
        finished = trip_tenant = None
        requeued = False
        with self._lock:
            self._release_waiters_locked(path)
            job = self._jobs.pop(path, None)
            if job is None:
                return False
            request = job.request
            err_class, transient = classify(exc)
            job.attempts += 1
            if (transient and job.attempts <= self.cfg.retries
                    and not self.breaker.tripped(request.tenant)):
                self.packer.discard(path)
                self.queue.requeue(job)
                requeued = True
            else:
                try:
                    exc.attempts = job.attempts  # manifest records attempts
                except AttributeError:
                    pass
                request.failed.append({
                    "video": path, "error_class": err_class,
                    "transient": transient, "message": str(exc)[:500],
                })
                finished = self._finish_request_locked(request)
                if self.breaker.record_failure(request.tenant):
                    # breaker state + queue drain stay atomic with the
                    # terminal count (submit checks tripped() under this
                    # lock); the per-job manifests + prints run after release
                    trip_tenant = request.tenant
                    trip_jobs = self.queue.drain_tenant(request.tenant)
        if requeued:
            print(f"[serve] [{err_class}] attempt {job.attempts} failed "
                  f"for {path}: {exc}; re-enqueued "
                  f"({self.cfg.retries + 1 - job.attempts} attempt(s) "
                  "left)")
            return True
        self._publish_result(finished)
        if trip_tenant is not None:
            self._fail_fast_tenant(trip_tenant, trip_jobs)
        return False

    def _fail_fast_tenant(self, tenant: str, jobs) -> None:
        """Breaker tripped: fail the tenant's already-drained queued videos
        without decoding (called with NO lock held — each fast failure
        writes a manifest line)."""
        self._emit("breaker_open", tenant=tenant,
                   failures=self.breaker.failures(tenant))
        self.metrics.inc("breaker_trips_total", tenant=tenant)
        print(f"[serve] tenant {tenant!r} breaker OPEN "
              f"({self.breaker.failures(tenant)} terminal failures): "
              f"failing {len(jobs)} queued video(s) fast; new submissions "
              "rejected until reload")
        for job in jobs:
            self._fail_job_fast(job, "tenant breaker open")

    def _fail_job_fast(self, job, why: str) -> None:
        exc = TenantBreakerOpen(
            f"{job.path}: {why} (tenant {job.request.tenant!r}); not "
            "attempted")
        # manifest the fast failure under the job's OWN model's output tree
        # (derivable without constructing a never-used model's extractor)
        model = job.feature_type or self.cfg.feature_type
        ex = self.sessions.peek_extractor(model)
        out_dir = (ex.output_dir if ex is not None
                   else feature_output_dir(self.cfg.output_path, model))
        try:
            record_failure(out_dir, job.path, exc)
        except OSError as e:
            print(f"warning: could not record failure for {job.path}: {e}",
                  file=sys.stderr)
        self.sessions.release_decode(job.path)  # may have been hint-scheduled
        # fast failures skip the extractor's _fail (no decode, no attempt)
        # so they journal AND count here — the lifecycle chain must still
        # terminate and the failure counter must agree with the journal's
        # video_failed stream during exactly the incident it exists for
        self._emit("video_failed", video=job.path, model=model,
                   error_class="TenantBreakerOpen", transient=False)
        self.metrics.inc("videos_failed_total", model=model,
                         error_class="TenantBreakerOpen")
        # a fast-failed ex-waiter still holds its consult-time cache key
        # (abspath-keyed, matching the memo — job.path is absolute by
        # admission, the abspath here is belt-and-braces)
        if ex is not None:
            ex._cache_keys.pop(os.path.abspath(job.path), None)
        with self._lock:
            self._jobs.pop(job.path, None)  # registered at pop; breaker-
            # drained queue jobs were never popped, so the default is taken
            job.request.failed.append({
                "video": job.path, "error_class": "TenantBreakerOpen",
                "transient": False, "message": str(exc)[:500],
            })
            finished = self._finish_request_locked(job.request)
        self._publish_result(finished)

    def _finish_request_locked(self, request: ServiceRequest,
                               force: bool = False):
        """Pop a completed request and build its result record (service lock
        HELD — callers pass the return to :meth:`_publish_result` after
        releasing). None when the request is still live."""
        if not request.complete and not force:
            return None
        record = request.result_record()
        if force and not request.complete:
            record["state"] = "aborted"  # drain unwound before completion
        self._requests.pop(request.request_id, None)
        # stay visible to status()/submit() until the record write lands —
        # a client polling the instant after completion must never see
        # "unknown request_id" for a request that just succeeded
        self._publishing[request.request_id] = record
        self._completed_requests += 1
        return (request, record)

    def _publish_result(self, finished) -> None:
        """Write + announce one finished request's record (NO lock held —
        the record write is disk I/O, and submitters on the ingest threads
        convoy on the service lock). Once a request left ``_requests`` its
        done/failed lists are final: no job references it, so reading them
        here is race-free; ``_publishing`` keeps it answerable meanwhile."""
        if finished is None:
            return
        request, record = finished
        published = False
        try:
            # post-extract / pre-publish chaos seam: a kill here leaves the
            # WAL entry unresolved, so the restarted daemon replays the
            # request, dedupes its done videos, and re-publishes the record
            fault_point("publish", request.request_id)
            write_request_result(self.notify_dir, request.request_id, record)
            published = True
        except Exception as e:  # noqa: BLE001 — fault-barrier: the notification is advisory; outputs + manifests already landed
            print(f"[serve] could not write result for "
                  f"{request.request_id}: {e}", file=sys.stderr)
        if published:
            # resolve only after the record landed: a failed publish keeps
            # the WAL entry live, and recovery re-publishes from the
            # done-manifests instead of losing the notification
            if self._wal is not None and request.wal_logged:
                self._wal.resolve(
                    request.request_id,
                    "done" if record.get("state") == "done" else "failed")
            self._cleanup_spool(request)
        with self._lock:
            self._publishing.pop(request.request_id, None)
        self._emit("request_done", request=request.request_id,
                   tenant=request.tenant, state=record["state"],
                   done=len(request.done), failed=len(request.failed))
        self.metrics.inc("requests_total", state=record["state"])
        print(f"[serve] request {request.request_id} {record['state']}: "
              f"{len(request.done)} done, {len(request.failed)} failed")
        self._autoscale_tick()

    def _cleanup_spool(self, request: ServiceRequest) -> None:
        """Spool hygiene: drop the claimed ``.accepted`` request file once
        its result record is published (and the WAL entry resolved) — the
        result record is the durable trace from here on. ``--spool_retain``
        keeps the files for debugging."""
        if (request.source != "spool" or not self.cfg.spool_dir
                or self.cfg.spool_retain):
            return
        try:
            os.remove(accepted_path(self.cfg.spool_dir, request.request_id))
        except OSError:
            pass  # already gone, or submitted pre-upgrade under a raw name

    # --- hung-step watchdog (--step_watchdog_sec) ---------------------------

    def _watchdog_loop(self) -> None:
        """Monitor thread: flag the daemon when the serving loop has not
        stepped past the threshold. Communication is Events only (SETS
        ``_stalled``; the daemon thread clears it and requeues) — the
        monitor never touches request state, so a false positive during a
        legitimately long first-traffic compile costs one transient requeue
        of the in-flight batch, not correctness."""
        thresh = self.cfg.step_watchdog_sec
        poll = min(max(thresh / 4.0, 0.05), 1.0)
        while not self._watchdog_stop.wait(poll):
            age = time.monotonic() - self._last_step
            if age > thresh and not self._stalled.is_set():
                self._stalled.set()
                self._emit("watchdog_stale", age_sec=round(age, 3),
                           threshold_sec=thresh)
                self.metrics.inc("watchdog_trips_total")
                print(f"[serve] watchdog: no step for {age:.1f}s "
                      f"(threshold {thresh}s); in-flight videos will fail "
                      "transiently and requeue once the loop resumes",
                      file=sys.stderr)

    def _requeue_stalled(self) -> None:
        """The watchdog tripped while the previous step was wedged: turn the
        stall into a transient batch failure. Every in-flight video fails
        through the session's slot-attribution path (the same machinery a
        poisoned co-packed batch uses), so victims requeue with their retry
        budgets and breakers charge nobody for a device stall."""
        with self._lock:
            victims = [(path, job.feature_type or self.cfg.feature_type)
                       for path, job in self._jobs.items()]
        if not victims:
            return
        print(f"[serve] watchdog: failing {len(victims)} stalled in-flight "
              f"video(s) transiently for requeue", file=sys.stderr)
        for path, model in victims:
            self.sessions.release_decode(path)
            self.session.fail(path, model, DeviceError(
                f"{path}: device step stalled past "
                f"--step_watchdog_sec={self.cfg.step_watchdog_sec}; "
                "attempt abandoned"))
        self.session.emit_completed(reap_limit=0)

    def _autoscale_tick(self) -> None:
        """Between requests: act on the interval's decode-starvation signal.
        Measure + snapshot swap + decide run under the service lock as one
        unit (request completions land from the daemon thread AND submit-
        time all-resumed completions from ingest threads — a torn interval
        would regress the snapshot and double-apply a resize step); decide()
        is pure arithmetic, so only the print and the internally-locked
        ``pool.resize`` stay outside."""
        pool = self.sessions.decode_pool
        if self._autoscaler is None or pool is None:
            return
        # read the pool's idle-permit headroom BEFORE taking the service
        # lock: spare_permits() takes the pool's resize lock, and the
        # declared lock order has no service→resize edge to lean on
        spare = pool.spare_permits()
        with self._lock:
            now = time.perf_counter()
            decode = self.ex.clock.seconds.get("decode", 0.0)
            real, slots = self.packer.real_slots, self.packer.dispatched_slots
            t0, d0, r0, s0 = self._as_snapshot
            self._as_snapshot = (now, decode, real, slots)
            d_slots = slots - s0
            occupancy = (real - r0) / d_slots if d_slots else 1.0
            current = pool.workers
            new = self._autoscaler.decide(occupancy, decode - d0, now - t0,
                                          current,
                                          dispatched_slots=d_slots,
                                          spare_permits=spare)
        if new != current:
            print(f"[serve] decode autoscale: {current} → {new} "
                  f"worker(s) (interval occupancy {occupancy:.1%}, decode "
                  f"{decode - d0:.2f}s of {now - t0:.2f}s)")
            self._emit("autoscale", workers_from=current, workers_to=new,
                       occupancy=round(occupancy, 4), spare_permits=spare)
            pool.resize(new)
            self.metrics.set_gauge("decode_workers", new)

    def _quiescent(self) -> bool:
        with self._lock:
            return (self.queue.pending() == 0 and not self._jobs
                    and not self.packer.has_pending()
                    and not self.sessions.pending_writes())

    def _load_tenants_config(self, initial: bool = False) -> None:
        path = os.path.join(self.cfg.spool_dir, SPOOL_TENANTS_FILE)
        if not os.path.exists(path):
            return
        try:
            with open(path) as f:
                self.queue.configure(json.load(f))
            print(f"[serve] tenant config loaded from {path}")
        except (OSError, ValueError) as e:
            msg = f"[serve] bad tenant config {path}: {e}"
            if initial:
                raise ValueError(msg) from e
            print(msg + " — keeping the previous config", file=sys.stderr)

    # --- socket API ----------------------------------------------------------

    def status(self, request_id: str) -> dict:
        with self._lock:
            request = self._requests.get(request_id)
            if request is not None:
                return {"ok": True, "state": request.state,
                        "tenant": request.tenant,
                        "feature_type": request.feature_type,
                        "videos": len(request.videos),
                        "done": len(request.done),
                        "failed": len(request.failed)}
            publishing = self._publishing.get(request_id)
            if publishing is not None:
                # completed, record write still in flight: answer from the
                # in-memory record rather than racing the disk
                return {"ok": True, **publishing}
        path = request_result_path(self.notify_dir, request_id)
        if os.path.exists(path):
            try:
                with open(path) as f:
                    record = json.load(f)
                return {"ok": True, **record}
            except (OSError, ValueError) as e:
                return {"ok": False, "error": f"unreadable result: {e}"}
        return {"ok": False, "error": f"unknown request_id {request_id!r}"}

    def _transfer_stats(self) -> dict:
        """Host→device staging counters from the service-lifetime clock plus
        the staging ring's reuse/backpressure accounting."""
        clock = self.ex.clock
        seconds = clock.seconds.get("transfer", 0.0) if clock else 0.0
        nbytes = clock.bytes.get("transfer", 0) if clock else 0
        ring = self.ex._staging
        return {
            "seconds": round(seconds, 3),
            "bytes": int(nbytes),
            "mb_per_s": round(nbytes / seconds / 1e6, 2) if seconds else 0.0,
            "staging_buffers": ring.allocated,
            "staging_acquires": ring.acquires,
            "staging_evicted_geometries": ring.evicted_geometries,
            "staging_wait_sec": round(ring.wait_seconds, 3),
        }

    def stats(self) -> dict:
        pool = self.sessions.decode_pool
        seg_videos, seg_segments = (pool.segment_stats() if pool is not None
                                    else (0, 0))
        # per-model rollup: packer occupancy by model × completion counters
        # (only models that saw traffic appear — lazily-built extractors)
        model_occ = self.packer.model_stats()
        models = {}
        for name, counts in self.sessions.model_counts().items():
            models[name] = dict(counts)
            models[name].update(model_occ.get(name, {}))
        with self._lock:
            return {
                "ok": True,
                # payload version (docs/serving.md documents the field tree):
                # external scrapers pin this and treat a bump as a breaking
                # change; additive fields do not bump it
                "schema": 1,
                "feature_type": self.cfg.feature_type,
                "serving_models": list(self.models),
                "uptime_sec": round(time.monotonic() - self._started, 3),
                "draining": self._draining.is_set(),
                "live_requests": len(self._requests),
                "in_flight_videos": len(self._jobs),
                "queued_videos": self.queue.pending(),
                "completed_requests": self._completed_requests,
                "videos_ok": self.sessions.ok,
                "videos_failed": (self.sessions.failures
                                  + self._service_failures),
                # per-model occupancy/throughput (multi-model daemons: the
                # one-line answer to "is model B starving the mesh?")
                "models": models,
                "packing": {
                    "real_slots": self.packer.real_slots,
                    "dispatched_slots": self.packer.dispatched_slots,
                    "occupancy": round(self.packer.occupancy, 4),
                    # per-shape-bucket occupancy (operators watch a rare
                    # bucket starving without tailing the daemon log)
                    "buckets": self.packer.bucket_stats(),
                    "stale_flushes": self.packer.stale_flushes,
                    # ragged paged dispatch (parallel/pages.py; additive —
                    # no schema bump): page count, the deepest observed
                    # in-flight ring, and the page-level occupancy (real
                    # rows / dispatched page rows — the page_occupancy
                    # gauge's corpus-cumulative answer)
                    "pages_dispatched": self.packer.pages_dispatched,
                    "max_in_flight": self.packer.max_in_flight,
                    "page_occupancy": (round(self.packer.occupancy, 4)
                                       if self.packer.pages_dispatched
                                       else 0.0),
                },
                # host→device staging health (ingest fast path): operators
                # can tell a transfer-bound daemon from a decode-bound one
                # without tailing the log (seconds/bytes are defaultdict
                # .get reads — atomic enough against the daemon thread)
                "transfer": self._transfer_stats(),
                # per-stage wall seconds from the service-lifetime clock
                # (additive, no schema bump): the decode/transfer split that
                # tells WHERE preprocessing cost lives — --device_preproc
                # moves the per-frame PIL/DSP work out of the decode pool
                # and into the jitted step, and this is the operator-visible
                # meter for it (tools/service_smoke.py pins the section).
                # dict() snapshots atomically under the GIL before iterating
                # — the run loop may be inserting a first-seen stage key
                "stages": ({k: round(v, 3)
                            for k, v in dict(self.ex.clock.seconds).items()}
                           if self.ex.clock is not None else {}),
                "cache": (dict(self.ex._cache.stats(),
                               coalesced=self._coalescer.coalesced,
                               waiting=self._coalescer.waiting())
                          if self.ex._cache is not None
                          else {"enabled": False}),
                # admission durability (serve/wal.py): additive section, no
                # schema bump — durable flag, unresolved depth, compactions
                "wal": (self._wal.stats() if self._wal is not None
                        else {"enabled": False}),
                "decode_workers": pool.workers if pool is not None else 0,
                # segmented intra-video decode (additive, no schema bump):
                # videos split across permits and segment streams completed
                "segmented_decode": {
                    "videos": seg_videos,
                    "segments": seg_segments,
                },
                "tenants": self.queue.stats(),
                "breaker_open": list(self.breaker.open_tenants()),
                # per-tenant × per-model latency distributions (p50/p95/p99
                # + counts) from the live histograms — the after-the-fact
                # "why was tenant B's p99 bad?" answer the point-in-time
                # counters above cannot give; full bucket detail is on the
                # `metrics` op
                "latency": {
                    "e2e": self.metrics.summaries("e2e_latency_seconds"),
                    "queue_wait": self.metrics.summaries(
                        "queue_wait_seconds"),
                },
                "telemetry": (self.journal.stats() if self.journal is not None
                              else {"enabled": False}),
            }

    def healthz(self) -> dict:
        """Liveness + staleness, served from the API thread WITHOUT the
        service lock — a wedged daemon thread (or one stalled in a long
        first-traffic compile) still answers, and ``last_step_age_sec`` is
        how an operator tells the two apart. ``stale`` trips once the loop
        has not stepped for ``--healthz_stale_sec``; a legitimate cause
        (a 60 s flow compile) looks identical to a wedge by design — both
        mean "the daemon is not serving right now". The ``wal`` section is
        the durability signal: ``durable: false`` means admissions are
        being acknowledged WITHOUT a landed WAL record (ENOSPC degrade) and
        a crash would lose them — page on it."""
        now = time.monotonic()
        age = now - self._last_step
        return {
            "ok": True,
            "schema": 1,
            "uptime_sec": round(now - self._started, 3),
            "last_step_age_sec": round(age, 3),
            "stale": age > self.cfg.healthz_stale_sec,
            "stale_threshold_sec": self.cfg.healthz_stale_sec,
            "draining": self._draining.is_set(),
            "profiling": self._profiling,
            "wal": (self._wal.health() if self._wal is not None
                    else {"enabled": False}),
        }

    def _profile_op(self, action: str, trace_dir: Optional[str]) -> dict:
        """On-demand ``jax.profiler`` session in the LIVE daemon (`profile`
        op): start captures device/host activity from now, stop writes the
        trace for TensorBoard/XProf. Runs on the API thread —
        ``jax.profiler.start_trace`` is process-global, so it sees the
        daemon thread's device work."""
        import jax

        if action == "start":
            if self._profiling is not None:
                return {"ok": False, "error": f"already profiling into "
                                              f"{self._profiling}; stop first"}
            trace_dir = trace_dir or self.cfg.profile_dir or (
                os.path.join(self.cfg.telemetry_dir, "profile")
                if self.cfg.telemetry_dir else None)
            if not trace_dir:
                return {"ok": False,
                        "error": "no trace dir: pass {\"dir\": ...} or start "
                                 "the daemon with --profile_dir/"
                                 "--telemetry_dir"}
            try:
                os.makedirs(trace_dir, exist_ok=True)
                jax.profiler.start_trace(trace_dir)
            except Exception as e:  # noqa: BLE001 — fault-barrier: a profiler that cannot start (backend quirk, bad dir) must report, not kill the API thread serving the live daemon
                return {"ok": False, "error": f"start_trace failed: {e}"}
            self._profiling = trace_dir
            self._emit("profile_start", dir=trace_dir)
            return {"ok": True, "profiling": trace_dir}
        if action == "stop":
            if self._profiling is None:
                return {"ok": False, "error": "not profiling; start first"}
            try:
                jax.profiler.stop_trace()
            except Exception as e:  # noqa: BLE001 — fault-barrier: a failing stop (full trace disk mid-export) must report over the socket and stay RETRYABLE, not dead-end the op
                # keep _profiling set: jax's global session is usually still
                # live after a failed export, so a retried stop can succeed.
                # If jax says there IS no session (export failed after the
                # session ended), clear the flag so a fresh start works —
                # either way the op recovers without a daemon restart.
                if "not started" in str(e).lower() \
                        or "no profile" in str(e).lower():
                    self._profiling = None
                return {"ok": False, "error": f"stop_trace failed: {e}"}
            trace_dir, self._profiling = self._profiling, None
            self._emit("profile_stop", dir=trace_dir)
            return {"ok": True, "trace_dir": trace_dir}
        return {"ok": False,
                "error": "profile needs \"action\": \"start\" or \"stop\""}

    def handle_op(self, op: dict) -> dict:
        """Dispatch one socket-API operation (transport in :mod:`.ingest`)."""
        kind = op.get("op")
        if kind == "ping":
            return {"ok": True}
        if kind == "healthz":
            return self.healthz()
        if kind == "metrics":
            # full registry dump + Prometheus text exposition from ONE
            # series copy: scrapers take the text, humans/tools the
            # structured snapshot
            snapshot, text = self.metrics.export()
            return {"ok": True, "schema": 1,
                    "metrics": snapshot, "prometheus": text}
        if kind == "profile":
            return self._profile_op(str(op.get("action", "")), op.get("dir"))
        if kind == "submit":
            try:
                request = self.submit(op, request_id=op.get("request_id"),
                                      source="socket")
            except RequestRejected as e:
                self._emit("request_rejected",
                           request=op.get("request_id"),
                           reason=str(e)[:200])
                return {"ok": False, "error": str(e)}
            return {"ok": True, "request_id": request.request_id,
                    "state": request.state}
        if kind == "status":
            return self.status(str(op.get("request_id", "")))
        if kind == "stats":
            return self.stats()
        if kind == "drain":
            self.request_drain()
            return {"ok": True, "draining": True}
        if kind == "reload":
            # applied by the daemon loop before its next pop (thread safety:
            # reload mutates scheduler weights and breakers)
            self._hup.set()
            return {"ok": True, "reload": "scheduled"}
        return {"ok": False, "error": f"unknown op {kind!r}"}


def serve(cfg) -> int:
    """Run the daemon for ``cfg`` (``--serve`` / ``python -m …serve``)."""
    from ..extractors import get_extractor

    if not cfg.spool_dir:
        print("--serve requires --spool_dir (the watched request directory)",
              file=sys.stderr)
        return 2
    os.makedirs(cfg.spool_dir, exist_ok=True)
    extractor = get_extractor(cfg)
    try:
        service = ExtractionService(extractor)
    except ValueError as e:
        print(str(e), file=sys.stderr)
        return 2
    watcher = SpoolWatcher(cfg.spool_dir, service,
                           poll_interval=cfg.spool_poll_sec)
    sock_path = cfg.socket_path
    if sock_path is None:
        sock_path = os.path.join(cfg.spool_dir, "control.sock")
    api = (SocketAPI(sock_path, service)
           if sock_path and sock_path.lower() != "none" else None)

    def on_term(signum, frame):
        if service._draining.is_set():
            raise KeyboardInterrupt  # second signal: abort now
        service.request_drain()

    def on_hup(signum, frame):
        service._hup.set()

    if threading.current_thread() is threading.main_thread():
        signal.signal(signal.SIGTERM, on_term)
        signal.signal(signal.SIGINT, on_term)
        signal.signal(signal.SIGHUP, on_hup)
    # replay a crashed predecessor's unresolved admissions BEFORE the ingest
    # transports open: recovered jobs hold their original seqs, and no fresh
    # submission can race the seq fast-forward
    service.recover()
    watcher.start()
    if api is not None:
        api.start()
        print(f"[serve] socket API at {sock_path}")
    print(f"[serve] watching {cfg.spool_dir} "
          f"(results → {service.notify_dir}); SIGTERM drains, SIGHUP "
          "reloads")
    try:
        return service.run()
    finally:
        watcher.stop()
        if api is not None:
            api.stop()


def main(argv=None) -> int:
    """``python -m video_features_tpu.serve`` — the batch CLI surface with
    ``--serve`` implied."""
    from ..cli import parse_args
    from ..run import _honor_jax_platforms_env

    _honor_jax_platforms_env()
    cfg = parse_args(list(argv) if argv is not None else None)
    if not cfg.serve:
        cfg = cfg.replace(serve=True)
        try:
            cfg.validate()
        except ValueError as e:
            print(str(e), file=sys.stderr)
            return 2
    return serve(cfg)
