"""Ingest transports for the extraction daemon: spool directory + local socket.

Two ways work enters the service, both translating into
:meth:`..serve.daemon.ExtractionService.submit`:

- **spool directory** (:class:`SpoolWatcher`): a tenant drops
  ``<request_id>.json`` into the watched directory (write-to-temp + rename —
  the watcher treats every ``*.json`` as complete). Accepted files are
  renamed ``<name>.json.accepted``; rejects rename to ``.rejected`` AND get
  a ``rejected``-state result record in the notify directory, so a submitter
  only ever polls one place. ``tenants.json`` is the scheduler's config
  file, not a request — skipped by name.
- **local socket** (:class:`SocketAPI`): newline-delimited JSON over a Unix
  stream socket, one request per connection. Ops: ``submit``, ``status``,
  ``stats``, ``drain``, ``reload``, ``ping``. The daemon's
  ``handle_op(dict) -> dict`` does the work; this class is transport only.

Both run one daemon thread each and publish exclusively through the
service's locked methods — the threads themselves store nothing shared
(vftlint ``thread-shared-state``: declared in THREAD_MODULES, no
SHARED_WRITES entries needed).
"""

from __future__ import annotations

import json
import os
import socket
import sys
import threading
from typing import Optional

from .request import RequestRejected

SPOOL_TENANTS_FILE = "tenants.json"


def accepted_path(spool_dir: str, request_id: str) -> str:
    """Where an accepted spool request's claimed file lives — the daemon
    removes it when the request's result record is published (spool hygiene,
    unless ``--spool_retain``)."""
    return os.path.join(spool_dir, request_id + ".json.accepted")


class SpoolWatcher:
    """Poll a spool directory for per-tenant request files."""

    def __init__(self, spool_dir: str, service, poll_interval: float = 0.25):
        self.spool_dir = spool_dir
        self._service = service
        self._poll = poll_interval
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def scan_once(self) -> int:
        """One pass over the spool; returns how many files were consumed.

        Callable without the thread (tests, and the daemon's final pre-drain
        sweep). Consumed = renamed away, accepted or not; a rename failure
        leaves the file for the next pass.
        """
        try:
            names = sorted(os.listdir(self.spool_dir))
        except OSError as e:
            print(f"[serve] cannot list spool {self.spool_dir}: {e}",
                  file=sys.stderr)
            return 0
        consumed = 0
        for name in names:
            if not name.endswith(".json") or name == SPOOL_TENANTS_FILE:
                continue
            path = os.path.join(self.spool_dir, name)
            request_id = name[: -len(".json")]
            try:
                with open(path) as f:
                    payload = json.load(f)
            except (OSError, ValueError) as e:
                consumed += self._finish(path, ".rejected")
                self._service.reject(request_id, f"unreadable request file: "
                                     f"{e}", source="spool")
                continue
            # claim BEFORE submitting: if the rename fails the file simply
            # waits for the next pass un-submitted — renaming after a
            # successful submit could re-submit (and eventually re-extract)
            # the whole request when the rename fails
            if not self._finish(path, ".accepted"):
                continue
            consumed += 1
            try:
                self._service.submit(payload, request_id=request_id,
                                     source="spool")
            except RequestRejected as e:
                self._rename(path + ".accepted", path + ".rejected")
                self._service.reject(request_id, str(e), source="spool",
                                     payload=payload)
        return consumed

    @staticmethod
    def _finish(path: str, suffix: str) -> int:
        return SpoolWatcher._rename(path, path + suffix)

    @staticmethod
    def _rename(src: str, dst: str) -> int:
        try:
            os.replace(src, dst)
            return 1
        except OSError as e:
            print(f"[serve] cannot rename {src}: {e}", file=sys.stderr)
            return 0

    def start(self) -> None:
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="spool-watcher")
        self._thread.start()

    def _loop(self) -> None:
        while not self._stop.is_set():
            self.scan_once()
            self._stop.wait(self._poll)

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2.0)
            self._thread = None


class SocketAPI:
    """Line-JSON submit/status API on a Unix stream socket."""

    def __init__(self, socket_path: str, service):
        self.socket_path = socket_path
        self._service = service
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._srv: Optional[socket.socket] = None

    def start(self) -> None:
        if os.path.exists(self.socket_path):
            os.unlink(self.socket_path)  # stale socket from a previous run
        srv = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        srv.bind(self.socket_path)
        srv.listen(8)
        srv.settimeout(0.2)  # keeps the accept loop stop-responsive
        self._srv = srv
        self._thread = threading.Thread(target=self._serve, daemon=True,
                                        name="socket-api")
        self._thread.start()

    def _serve(self) -> None:
        srv = self._srv
        while not self._stop.is_set():
            try:
                conn, _ = srv.accept()
            except socket.timeout:
                continue
            except OSError:
                return  # socket closed under us at stop()
            try:
                conn.settimeout(2.0)
                self._handle(conn)
            except Exception as e:  # noqa: BLE001 — fault-barrier: one bad client connection must not kill the API thread
                print(f"[serve] socket client error: {e}", file=sys.stderr)
            finally:
                conn.close()

    def _handle(self, conn: socket.socket) -> None:
        buf = b""
        while b"\n" not in buf and len(buf) < 1 << 20:
            chunk = conn.recv(65536)
            if not chunk:
                break
            buf += chunk
        line = buf.split(b"\n", 1)[0].strip()
        if not line:
            return
        try:
            op = json.loads(line.decode("utf-8"))
            if not isinstance(op, dict):
                raise ValueError("request must be a JSON object")
        except ValueError as e:
            response = {"ok": False, "error": f"bad request: {e}"}
        else:
            response = self._service.handle_op(op)
        conn.sendall(json.dumps(response).encode("utf-8") + b"\n")

    def stop(self) -> None:
        self._stop.set()
        if self._srv is not None:
            try:
                self._srv.close()
            except OSError:
                pass
            self._srv = None
        if self._thread is not None:
            self._thread.join(timeout=2.0)
            self._thread = None
        try:
            os.unlink(self.socket_path)
        except OSError:
            pass


def socket_request(socket_path: str, op: dict, timeout: float = 5.0) -> dict:
    """One client round-trip (tools/tests; also the cheapest CLI client)."""
    with socket.socket(socket.AF_UNIX, socket.SOCK_STREAM) as s:
        s.settimeout(timeout)
        s.connect(socket_path)
        s.sendall(json.dumps(op).encode("utf-8") + b"\n")
        buf = b""
        while b"\n" not in buf:
            chunk = s.recv(65536)
            if not chunk:
                break
            buf += chunk
    return json.loads(buf.split(b"\n", 1)[0].decode("utf-8"))
