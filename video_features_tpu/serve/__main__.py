"""``python -m video_features_tpu.serve`` — run the extraction daemon."""

import sys

from .daemon import main

if __name__ == "__main__":
    sys.exit(main())
