"""Always-on extraction service (``--serve`` / ``python -m …serve``).

The batch CLI runs to completion; this package wraps the same extractors
and corpus packer in a long-lived daemon: an ingest layer (spool directory +
local socket API, :mod:`.ingest`) enqueues per-tenant requests, an
admission/scheduling layer (:mod:`.scheduler`) with quotas and weighted-fair
+ deadline ordering decides whose video feeds the packer's warm slot queues
next, and a lifecycle layer (:mod:`.daemon`) handles graceful drain and
SIGHUP reload. docs/serving.md is the runbook.
"""

from .autoscale import DecodeAutoscaler
from .daemon import ExtractionService, serve
from .ingest import SocketAPI, SpoolWatcher, socket_request
from .request import RequestRejected, ServiceRequest, parse_request
from .scheduler import RequestQueue
from .wal import AdmissionLog

__all__ = [
    "AdmissionLog",
    "DecodeAutoscaler",
    "ExtractionService",
    "RequestQueue",
    "RequestRejected",
    "ServiceRequest",
    "SocketAPI",
    "SpoolWatcher",
    "parse_request",
    "serve",
    "socket_request",
]
