"""Decode-pool autoscaling from the PR 5 decode-starvation signal.

ROADMAP item 4, first step: ``--decode_workers 0`` means *auto*. The packed
stage report already measures the two numbers that matter — packing
occupancy (real clips per dispatched device slot) and host seconds blocked
on decode — and :func:`..utils.metrics.decode_starvation_warning` already
encodes the diagnosis. This module acts on it: between requests the daemon
feeds the interval's deltas to :meth:`DecodeAutoscaler.decide`, which grows
the pool by one when the interval was decode-starved (padding burned while
the host sat in the frame stream) and shrinks by one when decode was nearly
free (idle worker threads + their buffered frames are host RAM someone else
could use). One step per decision keeps the loop stable — the signal is
noisy per-interval, and the pool resize itself perturbs the next interval.
"""

from __future__ import annotations

import os
from typing import Optional

from ..utils.metrics import STARVED_DECODE_FRACTION, STARVED_OCCUPANCY

# decode below this fraction of interval wall = the pool is oversized
IDLE_DECODE_FRACTION = 0.1
# ignore intervals too small to diagnose (one short request, noise)
MIN_INTERVAL_SLOTS = 4


class DecodeAutoscaler:
    """Pure decision function + bounds; the daemon owns the measurement."""

    def __init__(self, min_workers: int = 1,
                 max_workers: Optional[int] = None):
        if max_workers is None:
            max_workers = max(min_workers, os.cpu_count() or 4)
        if not (1 <= min_workers <= max_workers):
            raise ValueError("need 1 <= min_workers <= max_workers")
        self.min_workers = min_workers
        self.max_workers = max_workers

    def decide(self, occupancy: float, decode_seconds: float,
               wall_seconds: float, current: int,
               dispatched_slots: int = MIN_INTERVAL_SLOTS,
               spare_permits: int = 0) -> int:
        """New pool size for the next interval.

        ``occupancy``/``decode_seconds``/``wall_seconds``/``dispatched_slots``
        are THIS interval's deltas, not run totals — an old starved interval
        must not keep growing a pool that already caught up.

        ``spare_permits`` is the pool's CURRENT idle-permit headroom
        (:meth:`..parallel.pipeline.DecodePrefetcher.spare_permits`). A
        decode-starved interval with idle permits means width is not the
        bottleneck — few long videos are pinning the pipeline at
        single-stream decode speed — so the right move is letting segmented
        decode spend the permits that already exist, not growing a pool
        that cannot use the workers it has.
        """
        if wall_seconds <= 0 or dispatched_slots < MIN_INTERVAL_SLOTS:
            return current
        decode_fraction = decode_seconds / wall_seconds
        if (occupancy < STARVED_OCCUPANCY
                and decode_fraction >= STARVED_DECODE_FRACTION):
            if spare_permits > 0:
                return current  # segment the current videos instead
            return min(current + 1, self.max_workers)
        if decode_fraction <= IDLE_DECODE_FRACTION:
            return max(current - 1, self.min_workers)
        return current
