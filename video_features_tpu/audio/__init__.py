from .melspec import log_mel_spectrogram, waveform_to_examples, wav_to_examples

__all__ = ["log_mel_spectrogram", "waveform_to_examples", "wav_to_examples"]
