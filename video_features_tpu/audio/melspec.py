"""VGGish log-mel frontend: waveform → (N, 96, 64) example patches (host numpy).

Behavioral spec — ``/root/reference/models/vggish/vggish_src/``:
- constants (``vggish_params.py:21-35``): 16 kHz, 25 ms periodic-Hann window,
  10 ms hop, 64 HTK-mel bins over 125–7500 Hz, log offset 0.01, 0.96 s example
  windows with no overlap;
- strided no-pad framing (``mel_features.py:21-45``), periodic Hann
  (``:48-68``), |rfft| with fft_length = 2^ceil(log2(400)) = 512 (``:71-92``,
  ``:214``), HTK mel weight matrix with zeroed DC bin (``:114-189``),
  log(mel + 0.01) (``:192-223``);
- example framing into non-overlapping (96, 64) patches (``vggish_input.py:27-65``);
- wav read: int16 → /32768.0, stereo averaged to mono, resampled to 16 kHz
  (``vggish_input.py:68-87``) with the same kaiser-windowed-sinc algorithm the
  reference pins (:mod:`video_features_tpu.audio.resample`).

This numpy implementation is the default host path AND the parity oracle for
the device-side pipeline: under ``--device_preproc`` the host ships raw
(N, 15600) PCM slabs (:func:`waveform_to_pcm_slabs`) and the log-mel runs as a
fused jitted prologue (:mod:`video_features_tpu.ops.audio`), pinned ≤2e-5
against this module's float64 math in tests/test_device_preproc.py.
"""

from __future__ import annotations

import numpy as np

SAMPLE_RATE = 16000
STFT_WINDOW_SECS = 0.025
STFT_HOP_SECS = 0.010
NUM_MEL_BINS = 64
MEL_MIN_HZ = 125.0
MEL_MAX_HZ = 7500.0
LOG_OFFSET = 0.01
EXAMPLE_WINDOW_SECS = 0.96
EXAMPLE_HOP_SECS = 0.96

# --device_preproc wire geometry (ops/audio.py consumes these): one (96, 64)
# example reads 95·160 + 400 = 15600 samples and the next example starts
# 96·160 = 15360 samples later. Both of melspec's tail-dropping framing
# stages (samples→STFT frames, frames→examples) admit example k iff
# n ≥ k·15360 + 15600 — the same predicate as framing the raw waveform
# directly with (15600, 15360), so PCM slabs are example-for-example
# equivalent to host log-mel examples (pinned in tests/test_device_preproc.py).
SAMPLES_PER_EXAMPLE = 15600
EXAMPLE_HOP_SAMPLES = 15360

_MEL_BREAK_FREQUENCY_HERTZ = 700.0
_MEL_HIGH_FREQUENCY_Q = 1127.0


def frame(data: np.ndarray, window_length: int, hop_length: int) -> np.ndarray:
    """Strided framing, incomplete tail dropped (mel_features.py:21-45)."""
    num_samples = data.shape[0]
    num_frames = 1 + int(np.floor((num_samples - window_length) / hop_length))
    if num_frames <= 0:
        return np.zeros((0, window_length) + data.shape[1:], data.dtype)
    shape = (num_frames, window_length) + data.shape[1:]
    strides = (data.strides[0] * hop_length,) + data.strides
    return np.lib.stride_tricks.as_strided(data, shape=shape, strides=strides)


def periodic_hann(window_length: int) -> np.ndarray:
    """Full-cycle raised cosine (not numpy's symmetric hanning)."""
    return 0.5 - 0.5 * np.cos(2 * np.pi / window_length * np.arange(window_length))


def stft_magnitude(signal: np.ndarray, fft_length: int, hop_length: int,
                   window_length: int) -> np.ndarray:
    frames = frame(signal, window_length, hop_length)
    return np.abs(np.fft.rfft(frames * periodic_hann(window_length), int(fft_length)))


def hertz_to_mel(frequencies_hertz):
    return _MEL_HIGH_FREQUENCY_Q * np.log(
        1.0 + np.asarray(frequencies_hertz, np.float64) / _MEL_BREAK_FREQUENCY_HERTZ
    )


def spectrogram_to_mel_matrix(num_mel_bins: int, num_spectrogram_bins: int,
                              audio_sample_rate: float, lower_edge_hertz: float,
                              upper_edge_hertz: float) -> np.ndarray:
    """(num_spectrogram_bins, num_mel_bins) triangular HTK weights, linear in mel,
    DC bin zeroed (mel_features.py:114-189)."""
    nyquist = audio_sample_rate / 2.0
    if not 0.0 <= lower_edge_hertz < upper_edge_hertz <= nyquist:
        raise ValueError(
            f"bad mel edges: 0 <= {lower_edge_hertz} < {upper_edge_hertz} <= {nyquist}"
        )
    bins_mel = hertz_to_mel(np.linspace(0.0, nyquist, num_spectrogram_bins))
    edges_mel = np.linspace(hertz_to_mel(lower_edge_hertz),
                            hertz_to_mel(upper_edge_hertz), num_mel_bins + 2)
    lower = edges_mel[:-2][None, :]
    center = edges_mel[1:-1][None, :]
    upper = edges_mel[2:][None, :]
    lower_slope = (bins_mel[:, None] - lower) / (center - lower)
    upper_slope = (upper - bins_mel[:, None]) / (upper - center)
    weights = np.maximum(0.0, np.minimum(lower_slope, upper_slope))
    weights[0, :] = 0.0
    return weights


def log_mel_spectrogram(data: np.ndarray, audio_sample_rate: float = SAMPLE_RATE,
                        log_offset: float = LOG_OFFSET,
                        window_length_secs: float = STFT_WINDOW_SECS,
                        hop_length_secs: float = STFT_HOP_SECS,
                        num_mel_bins: int = NUM_MEL_BINS,
                        lower_edge_hertz: float = MEL_MIN_HZ,
                        upper_edge_hertz: float = MEL_MAX_HZ) -> np.ndarray:
    window_length = int(round(audio_sample_rate * window_length_secs))
    hop_length = int(round(audio_sample_rate * hop_length_secs))
    fft_length = 2 ** int(np.ceil(np.log(window_length) / np.log(2.0)))
    spectrogram = stft_magnitude(data, fft_length, hop_length, window_length)
    mel = spectrogram @ spectrogram_to_mel_matrix(
        num_mel_bins, spectrogram.shape[1], audio_sample_rate,
        lower_edge_hertz, upper_edge_hertz)
    return np.log(mel + log_offset)


def _mono_16k(data: np.ndarray, sample_rate: float) -> np.ndarray:
    """Shared front half of both wire formats: stereo → mono mean, resample
    to 16 kHz with the reference-pinned kaiser-windowed sinc."""
    if data.ndim > 1:
        data = np.mean(data, axis=1)
    if sample_rate != SAMPLE_RATE:
        from .resample import output_length, resample

        if output_length(data.shape[0], sample_rate, SAMPLE_RATE) < 1:
            data = np.zeros(0, np.float64)  # degenerate/empty audio track:
            # keep the (0, ...) empty contract of the 16 kHz path
        else:
            data = resample(data, sample_rate, SAMPLE_RATE)
    return data


def waveform_to_examples(data: np.ndarray, sample_rate: float) -> np.ndarray:
    """[-1,1] waveform (mono or channels-last stereo) → (N, 96, 64) float32."""
    data = _mono_16k(data, sample_rate)
    log_mel = log_mel_spectrogram(data)
    features_rate = 1.0 / STFT_HOP_SECS
    window = int(round(EXAMPLE_WINDOW_SECS * features_rate))
    hop = int(round(EXAMPLE_HOP_SECS * features_rate))
    return frame(log_mel, window, hop).astype(np.float32)


def waveform_to_pcm_slabs(data: np.ndarray, sample_rate: float) -> np.ndarray:
    """[-1,1] waveform → (N, 15600) float32 raw-PCM example slabs.

    The ``--device_preproc`` wire format: slab k covers 16 kHz samples
    [k·15360, k·15360 + 15600) and :func:`video_features_tpu.ops.audio.
    log_mel_examples` turns the batch into (N, 96, 64) log-mel on device.
    Example-for-example equivalent to :func:`waveform_to_examples` (same
    mono/resample front half; framing identity documented at
    SAMPLES_PER_EXAMPLE above).
    """
    data = _mono_16k(data, sample_rate)
    return frame(np.ascontiguousarray(data),
                 SAMPLES_PER_EXAMPLE, EXAMPLE_HOP_SAMPLES).astype(np.float32)


def _read_wav(wav_path: str) -> tuple:
    from scipy.io import wavfile

    sr, data = wavfile.read(wav_path)
    if data.dtype != np.int16:
        raise ValueError(f"{wav_path}: expected 16-bit PCM, got {data.dtype}")
    return sr, data / 32768.0


def wav_to_examples(wav_path: str) -> np.ndarray:
    """16-bit PCM wav → examples (vggish_input.py:74-87 semantics via scipy)."""
    sr, data = _read_wav(wav_path)
    return waveform_to_examples(data, sr)


def wav_to_pcm_slabs(wav_path: str) -> np.ndarray:
    """16-bit PCM wav → (N, 15600) float32 slabs (``--device_preproc`` wire)."""
    sr, data = _read_wav(wav_path)
    return waveform_to_pcm_slabs(data, sr)
