"""Band-limited sinc resampling with resampy-compatible semantics (host numpy).

The reference's VGGish frontend resamples arbitrary-rate wavs to 16 kHz with
``resampy.resample`` (``/root/reference/models/vggish/vggish_src/vggish_input.py:84``),
i.e. Smith's band-limited interpolation with a Kaiser-windowed sinc prototype
("kaiser_best"). Round 1 substituted scipy's polyphase resampler, which is a
different filter — features on non-16 kHz inputs diverged from the reference
(ADVICE.md r1). This module re-implements the published algorithm (J. O. Smith,
"Digital audio resampling", and the resampy 0.2 kernel the reference pins) so
that path agrees too:

- prototype: ``rolloff · sinc(rolloff · t)`` on ``t ∈ [0, num_zeros]`` sampled at
  ``2^precision`` points per zero crossing, tapered by the right half of a
  symmetric Kaiser window;
- per output sample: two wings of taps around the fractional input time, window
  values linearly interpolated between table entries, gain scaled by the ratio
  when downsampling;
- output length ``floor(n · ratio)``; the fractional read time accumulates
  (``t_reg += 1/ratio``) rather than being recomputed, reproducing the
  reference kernel's float drift.

Vectorized over (output sample × tap) tiles instead of the reference's
per-sample JIT loop; ``tests/test_resample.py`` pins it to a literal
transcription of the kernel loop.
"""

from __future__ import annotations

from typing import Dict, Tuple

import numpy as np

# (num_zeros, precision, rolloff, kaiser beta) — the two filters resampy ships.
FILTERS: Dict[str, Tuple[int, int, float, float]] = {
    "kaiser_best": (64, 9, 0.9475937167399596, 14.769656459379492),
    "kaiser_fast": (16, 9, 0.85, 12.984585247040012),
}


def sinc_window(num_zeros: int, precision: int, rolloff: float,
                beta: float) -> np.ndarray:
    """Right half of the windowed-sinc interpolation table (length n+1)."""
    n = (2 ** precision) * num_zeros
    t = np.linspace(0, num_zeros, num=n + 1, endpoint=True)
    sinc = rolloff * np.sinc(rolloff * t)
    taper = np.kaiser(2 * n + 1, beta)[n:]
    return (sinc * taper).astype(np.float64)


_WIN_CACHE: Dict[str, np.ndarray] = {}


def _get_window(name: str) -> Tuple[np.ndarray, int]:
    if name not in FILTERS:
        raise ValueError(f"unknown filter {name!r} (have {sorted(FILTERS)})")
    if name not in _WIN_CACHE:
        num_zeros, precision, rolloff, beta = FILTERS[name]
        _WIN_CACHE[name] = sinc_window(num_zeros, precision, rolloff, beta)
    return _WIN_CACHE[name], 2 ** FILTERS[name][1]


def _time_register(n_out: int, time_increment: float) -> np.ndarray:
    """Accumulated (not recomputed) read times: t_reg[k] = k additions of the
    increment, matching the kernel's running float64 sum."""
    reg = np.zeros(n_out, np.float64)
    if n_out > 1:
        np.add.accumulate(np.full(n_out - 1, time_increment), out=reg[1:])
    return reg


def output_length(n_in: int, sr_orig: float, sr_new: float) -> int:
    """``floor(n · ratio)`` — the kernel's output-length rule, exposed so
    callers can detect degenerate (empty-output) inputs before calling."""
    return int(n_in * (float(sr_new) / float(sr_orig)))


def resample(x: np.ndarray, sr_orig: float, sr_new: float,
             filter: str = "kaiser_best", chunk: int = 8192) -> np.ndarray:
    """Resample 1-D ``x`` from ``sr_orig`` to ``sr_new``. float64 in/out math."""
    if sr_orig <= 0 or sr_new <= 0:
        raise ValueError("sample rates must be positive")
    x = np.asarray(x, np.float64)
    if x.ndim != 1:
        raise ValueError(f"expected mono 1-D signal, got shape {x.shape}")
    sample_ratio = float(sr_new) / float(sr_orig)
    if sample_ratio == 1.0:
        return x.copy()
    n_out = output_length(x.shape[0], sr_orig, sr_new)
    if n_out < 1:
        raise ValueError(f"input too short to resample (n={x.shape[0]}, ratio={sample_ratio})")

    interp_win, num_table = _get_window(filter)
    scale = min(1.0, sample_ratio)
    if sample_ratio < 1.0:
        interp_win = interp_win * sample_ratio  # downsampling: cutoff AND gain shrink
    interp_delta = np.zeros_like(interp_win)
    interp_delta[:-1] = np.diff(interp_win)
    index_step = int(scale * num_table)
    nwin = interp_win.shape[0]
    max_taps = nwin // max(index_step, 1) + 1

    t_reg = _time_register(n_out, 1.0 / sample_ratio)
    y = np.zeros(n_out, np.float64)
    taps = np.arange(max_taps)

    def wing(out, n, frac, source_idx_of_tap, tap_budget):
        """One wing: window-table lookup with linear interpolation, masked sum.

        ``source_idx_of_tap(n, i)`` maps tap i to an input index; ``tap_budget``
        is the per-sample cap from the signal boundary (n+1 left, len−n−1 right).
        """
        index_frac = frac * num_table
        offset = index_frac.astype(np.int64)
        eta = (index_frac - offset)[:, None]
        n_taps = np.minimum(tap_budget, (nwin - offset) // index_step)
        idx = offset[:, None] + taps[None, :] * index_step  # (chunk, max_taps)
        valid = taps[None, :] < n_taps[:, None]
        idx = np.where(valid, idx, 0)
        weights = (interp_win[idx] + eta * interp_delta[idx]) * valid
        src = np.clip(source_idx_of_tap(n[:, None], taps[None, :]), 0, x.shape[0] - 1)
        out += np.einsum("ij,ij->i", weights, x[src])

    for lo in range(0, n_out, chunk):
        sl = slice(lo, min(lo + chunk, n_out))
        reg = t_reg[sl]
        n = reg.astype(np.int64)
        frac = scale * (reg - n)
        wing(y[sl], n, frac, lambda nn, ii: nn - ii, n + 1)
        wing(y[sl], n, scale - frac, lambda nn, ii: nn + ii + 1, x.shape[0] - n - 1)
    return y
