"""Host-side I/O: video decode, audio read, file lists, output actions, ffmpeg shims."""
