"""Host-side video decoding: cv2 → RGB uint8 frame stream with timestamps.

Reproduces the reference decode-loop semantics (``extract_raft.py:110-151``,
``extract_i3d.py:175-219``): BGR→RGB conversion, the first-frame-missing workaround for
low-fps re-encodes, and per-frame ``CAP_PROP_POS_MSEC`` timestamps. fps changes use
ffmpeg when available (exact reference parity, ``utils/utils.py:147-169``); otherwise a
native timestamp-based frame sampler emulates ffmpeg's ``fps=`` filter without
re-encoding (faster, no disk round-trip — preferred on TPU hosts).

Decode is the canonical host-side hot loop (SURVEY.md §3.1); it feeds fixed-shape clip
batches to the device pipeline.
"""

from __future__ import annotations

import os
import sys
from dataclasses import dataclass
from typing import Callable, Iterator, Optional, Tuple

import cv2
import numpy as np

from . import ffmpeg as ffmpeg_io
from ..reliability import DecodeError, FfmpegError, RetryPolicy, fault_point, retry_call


@dataclass
class VideoMeta:
    path: str
    fps: float
    frame_count: int  # container header value; may be approximate
    width: int
    height: int


def probe_video(video_path: str) -> VideoMeta:
    """Container metadata, or a classified :class:`DecodeError` for corrupt input.

    cv2 "opens" many garbage files and reports ``fps=0, frame_count=0``;
    returning that meta poisons every downstream fps computation silently, so
    unopenable and degenerate containers raise instead.
    """
    fault_point("probe", video_path)
    cap = cv2.VideoCapture(video_path)
    try:
        if not cap.isOpened():
            raise DecodeError(f"{video_path}: cannot open container (corrupt or unsupported)")
        meta = VideoMeta(
            path=video_path,
            fps=cap.get(cv2.CAP_PROP_FPS),
            frame_count=int(cap.get(cv2.CAP_PROP_FRAME_COUNT)),
            width=int(cap.get(cv2.CAP_PROP_FRAME_WIDTH)),
            height=int(cap.get(cv2.CAP_PROP_FRAME_HEIGHT)),
        )
        if meta.fps <= 0 and meta.frame_count <= 0:
            raise DecodeError(
                f"{video_path}: container reports fps={meta.fps} and "
                f"frame_count={meta.frame_count} (corrupt header)"
            )
        return meta
    finally:
        cap.release()


def probe_geometries(paths, workers: int = 8) -> "dict[str, Tuple[int, int]]":
    """``{path: (width, height)}`` for every probeable container in ``paths``.

    Header-only (no frame decode) and probed ``workers``-wide — on a large
    corpus over network storage a serial sweep would stall the mesh for the
    sum of every container-open latency before extraction starts. The corpus
    packer's shape-bucket planner uses the result to choose padded bucket
    geometries up front. Unprobeable paths are skipped here, not failed: the
    real open will classify them with full per-video fault attribution
    (manifest record, retries, circuit breaker). Workers return values only
    (results are assembled on the calling thread — no cross-thread stores).
    """

    def probe_one(path):
        try:
            meta = probe_video(path)
        except (DecodeError, OSError):
            return None
        return path, (meta.width, meta.height)

    paths = list(paths)
    if workers > 1 and len(paths) > 1:
        from concurrent.futures import ThreadPoolExecutor

        with ThreadPoolExecutor(max_workers=min(workers, len(paths)),
                                thread_name_prefix="probe") as pool:
            results = list(pool.map(probe_one, paths))
    else:
        results = [probe_one(p) for p in paths]
    return dict(r for r in results if r is not None)


def _raw_frames(cap: cv2.VideoCapture) -> Iterator[Tuple[np.ndarray, float]]:
    """Yield (rgb_uint8_hwc, pos_msec) frames with the first-frame workaround.

    The reference tolerates exactly one missing first frame (re-encoded low-fps videos
    sometimes drop it — ``extract_raft.py:116-128``).
    """
    first_frame = True
    while cap.isOpened():
        frame_exists, bgr = cap.read()
        if first_frame:
            first_frame = False
            if frame_exists is False:
                continue
        if not frame_exists:
            cap.release()
            break
        rgb = cv2.cvtColor(bgr, cv2.COLOR_BGR2RGB)
        yield rgb, cap.get(cv2.CAP_PROP_POS_MSEC)


def resample_slots(src_index: int, src_fps: float, dst_fps: float) -> int:
    """Output slot an input frame maps to under ffmpeg's ``fps=`` filter.

    ffmpeg (libavfilter/vf_fps.c, default ``round=near`` = AV_ROUND_NEAR_INF)
    rescales each input pts into the output timebase rounding half away from
    zero: frame at ``t = i/src`` → slot ``⌊t·dst + 0.5⌋``.
    """
    return int(np.floor(src_index * dst_fps / src_fps + 0.5))


def _resampled_frames(
    frames: Iterator[Tuple[np.ndarray, float]], src_fps: float, dst_fps: float
) -> Iterator[Tuple[np.ndarray, float]]:
    """Emulate ffmpeg's ``fps=dst_fps`` filter on a decoded stream (no re-encode).

    Slot semantics (vf_fps.c): output slot ``j`` displays the LAST input frame
    whose rounded output pts (:func:`resample_slots`) is ≤ ``j`` — later frames
    mapping to an already-claimed slot replace nothing (dropped); gaps duplicate
    the previous frame. Timestamps follow the decode path's ``CAP_PROP_POS_MSEC``
    convention (timestamp *after* the frame): slot ``j`` → ``(j+1)/dst`` ms.
    """
    next_slot = 0
    prev: Optional[np.ndarray] = None
    for src_idx, (rgb, _pos) in enumerate(frames):
        slot = resample_slots(src_idx, src_fps, dst_fps)
        # slots strictly before this frame's slot belong to the previous frame
        while prev is not None and next_slot < slot:
            yield prev.copy(), (next_slot + 1) / dst_fps * 1000.0
            next_slot += 1
        prev = rgb  # claims slot max(slot, next_slot) unless a later frame does
    if prev is not None:
        yield prev.copy(), (next_slot + 1) / dst_fps * 1000.0


def open_video(
    video_path: str,
    extraction_fps: Optional[int] = None,
    tmp_path: str = "./tmp",
    keep_tmp_files: bool = False,
    use_ffmpeg: str = "auto",
    transform: Optional[Callable[[np.ndarray], np.ndarray]] = None,
    retries: int = 2,
    retry_backoff: float = 0.5,
) -> Tuple[VideoMeta, Iterator[Tuple[np.ndarray, float]]]:
    """Open a video; return (meta, iterator of (rgb_uint8_frame, pos_msec)).

    ``extraction_fps`` changes the effective frame rate: via ffmpeg re-encode when
    available (``use_ffmpeg='auto'``/'always'; exact reference parity) or via the
    native sampler ('never' or no ffmpeg binary). ``transform``, if given, is applied
    to each RGB frame on the host (e.g. PIL-bilinear resize).

    Failed ffmpeg re-encodes (transient: :class:`FfmpegError`) are retried
    ``retries`` times with exponential backoff starting at ``retry_backoff``
    seconds; if every attempt fails under ``use_ffmpeg='auto'``, the native
    sampler takes over (graceful degradation — the video survives at the cost
    of sampler-vs-reencode parity) while 'always' propagates the error.
    Unopenable/corrupt containers raise a classified :class:`DecodeError`.
    """
    if use_ffmpeg not in ("auto", "always", "never"):
        raise ValueError(f"use_ffmpeg must be 'auto'|'always'|'never', got {use_ffmpeg!r}")
    if not os.path.exists(video_path):
        raise FileNotFoundError(f"video does not exist: {video_path}")
    fault_point("decode", video_path)
    reencoded = None
    if extraction_fps is not None and use_ffmpeg != "never":
        if ffmpeg_io.have_ffmpeg():
            try:
                reencoded = retry_call(
                    lambda: ffmpeg_io.reencode_video_with_diff_fps(
                        video_path, tmp_path, extraction_fps
                    ),
                    RetryPolicy(attempts=retries + 1, base_delay=retry_backoff),
                )
                video_path = reencoded
            except FfmpegError as e:
                if use_ffmpeg == "always":
                    # the bounded retry above already owns this transient
                    # class; mark the escaping instance permanent so the
                    # per-video retry layer does not multiply the attempts
                    # (retries+1)^2-fold
                    e.transient = False
                    raise
                print(
                    f"warning: ffmpeg re-encode failed for {video_path} "
                    f"({e}); falling back to the native fps sampler",
                    file=sys.stderr,
                )
        elif use_ffmpeg == "always":
            raise RuntimeError(
                "use_ffmpeg='always' requested for fps resampling but ffmpeg is not "
                "installed; use use_ffmpeg='auto' to fall back to the native sampler"
            )

    cap = cv2.VideoCapture(video_path)
    if not cap.isOpened():
        cap.release()
        raise DecodeError(f"{video_path}: cannot open container (corrupt or unsupported)")
    src_fps = cap.get(cv2.CAP_PROP_FPS)
    src_count = int(cap.get(cv2.CAP_PROP_FRAME_COUNT))
    native_resample = extraction_fps is not None and reencoded is None
    if native_resample:
        if src_fps <= 0:
            cap.release()
            raise DecodeError(
                f"{video_path}: container reports fps={src_fps}; cannot resample to "
                f"{extraction_fps} fps without a source rate"
            )
        # approximate post-resampling frame count (same duration, new rate)
        out_count = int(round(src_count * float(extraction_fps) / src_fps)) if src_count > 0 else 0
    else:
        out_count = src_count
    meta = VideoMeta(
        path=video_path,
        fps=float(extraction_fps) if extraction_fps is not None else src_fps,
        frame_count=out_count,
        width=int(cap.get(cv2.CAP_PROP_FRAME_WIDTH)),
        height=int(cap.get(cv2.CAP_PROP_FRAME_HEIGHT)),
    )

    if native_resample:
        frames = _resampled_frames(_raw_frames(cap), src_fps, float(extraction_fps))
    else:
        frames = _raw_frames(cap)

    def _iter():
        try:
            for rgb, pos in frames:
                if transform is not None:
                    rgb = transform(rgb)
                yield rgb, pos
        finally:
            cap.release()
            if reencoded is not None and not keep_tmp_files and os.path.exists(reencoded):
                os.remove(reencoded)

    return meta, _iter()


def decode_all(video_path: str, **kw) -> Tuple[VideoMeta, np.ndarray, np.ndarray]:
    """Decode the whole video into (meta, frames (T,H,W,C) uint8, timestamps_ms (T,)).

    Whole-video decode is the R(2+1)D path (reference uses
    ``torchvision.io.read_video``, ``extract_r21d.py:102``); other models stream.
    """
    meta, frames = open_video(video_path, **kw)
    out, ts = [], []
    for rgb, pos in frames:
        out.append(rgb)
        ts.append(pos)
    if not out:
        h, w = max(meta.height, 0), max(meta.width, 0)
        return meta, np.zeros((0, h, w, 3), np.uint8), np.zeros((0,))
    return meta, np.stack(out), np.asarray(ts)
