"""Host-side video decoding: cv2 → RGB uint8 frame stream with timestamps.

Reproduces the reference decode-loop semantics (``extract_raft.py:110-151``,
``extract_i3d.py:175-219``): BGR→RGB conversion, the first-frame-missing workaround for
low-fps re-encodes, and per-frame ``CAP_PROP_POS_MSEC`` timestamps. fps changes use
ffmpeg when available (exact reference parity, ``utils/utils.py:147-169``); otherwise a
native timestamp-based frame sampler emulates ffmpeg's ``fps=`` filter without
re-encoding (faster, no disk round-trip — preferred on TPU hosts).

Decode is the canonical host-side hot loop (SURVEY.md §3.1); it feeds fixed-shape clip
batches to the device pipeline.
"""

from __future__ import annotations

import os
import sys
from dataclasses import dataclass, field
from typing import Callable, Iterator, List, Optional, Tuple

import cv2
import numpy as np

from . import ffmpeg as ffmpeg_io
from ..reliability import DecodeError, FfmpegError, RetryPolicy, fault_point, retry_call


@dataclass
class VideoMeta:
    path: str
    fps: float
    frame_count: int  # container header value; may be approximate
    width: int
    height: int


def probe_video(video_path: str) -> VideoMeta:
    """Container metadata, or a classified :class:`DecodeError` for corrupt input.

    cv2 "opens" many garbage files and reports ``fps=0, frame_count=0``;
    returning that meta poisons every downstream fps computation silently, so
    unopenable and degenerate containers raise instead.
    """
    fault_point("probe", video_path)
    cap = cv2.VideoCapture(video_path)
    try:
        if not cap.isOpened():
            raise DecodeError(f"{video_path}: cannot open container (corrupt or unsupported)")
        meta = VideoMeta(
            path=video_path,
            fps=cap.get(cv2.CAP_PROP_FPS),
            frame_count=int(cap.get(cv2.CAP_PROP_FRAME_COUNT)),
            width=int(cap.get(cv2.CAP_PROP_FRAME_WIDTH)),
            height=int(cap.get(cv2.CAP_PROP_FRAME_HEIGHT)),
        )
        if meta.fps <= 0 and meta.frame_count <= 0:
            raise DecodeError(
                f"{video_path}: container reports fps={meta.fps} and "
                f"frame_count={meta.frame_count} (corrupt header)"
            )
        return meta
    finally:
        cap.release()


def probe_geometries(paths, workers: int = 8) -> "dict[str, Tuple[int, int]]":
    """``{path: (width, height)}`` for every probeable container in ``paths``.

    Header-only (no frame decode) and probed ``workers``-wide — on a large
    corpus over network storage a serial sweep would stall the mesh for the
    sum of every container-open latency before extraction starts. The corpus
    packer's shape-bucket planner uses the result to choose padded bucket
    geometries up front. Unprobeable paths are skipped here, not failed: the
    real open will classify them with full per-video fault attribution
    (manifest record, retries, circuit breaker). Workers return values only
    (results are assembled on the calling thread — no cross-thread stores).
    """

    def probe_one(path):
        try:
            meta = probe_video(path)
        except (DecodeError, OSError):
            return None
        return path, (meta.width, meta.height)

    paths = list(paths)
    if workers > 1 and len(paths) > 1:
        from concurrent.futures import ThreadPoolExecutor

        with ThreadPoolExecutor(max_workers=min(workers, len(paths)),
                                thread_name_prefix="probe") as pool:
            results = list(pool.map(probe_one, paths))
    else:
        results = [probe_one(p) for p in paths]
    return dict(r for r in results if r is not None)


def _raw_frames(cap: cv2.VideoCapture) -> Iterator[Tuple[np.ndarray, float]]:
    """Yield (rgb_uint8_hwc, pos_msec) frames with the first-frame workaround.

    The reference tolerates exactly one missing first frame (re-encoded low-fps videos
    sometimes drop it — ``extract_raft.py:116-128``).
    """
    first_frame = True
    while cap.isOpened():
        frame_exists, bgr = cap.read()
        if first_frame:
            first_frame = False
            if frame_exists is False:
                continue
        if not frame_exists:
            cap.release()
            break
        rgb = cv2.cvtColor(bgr, cv2.COLOR_BGR2RGB)
        yield rgb, cap.get(cv2.CAP_PROP_POS_MSEC)


def resample_slots(src_index: int, src_fps: float, dst_fps: float) -> int:
    """Output slot an input frame maps to under ffmpeg's ``fps=`` filter.

    ffmpeg (libavfilter/vf_fps.c, default ``round=near`` = AV_ROUND_NEAR_INF)
    rescales each input pts into the output timebase rounding half away from
    zero: frame at ``t = i/src`` → slot ``⌊t·dst + 0.5⌋``.
    """
    return int(np.floor(src_index * dst_fps / src_fps + 0.5))


def _resampled_frames(
    frames: Iterator[Tuple[np.ndarray, float]], src_fps: float, dst_fps: float
) -> Iterator[Tuple[np.ndarray, float]]:
    """Emulate ffmpeg's ``fps=dst_fps`` filter on a decoded stream (no re-encode).

    Slot semantics (vf_fps.c): output slot ``j`` displays the LAST input frame
    whose rounded output pts (:func:`resample_slots`) is ≤ ``j`` — later frames
    mapping to an already-claimed slot replace nothing (dropped); gaps duplicate
    the previous frame. Timestamps follow the decode path's ``CAP_PROP_POS_MSEC``
    convention (timestamp *after* the frame): slot ``j`` → ``(j+1)/dst`` ms.
    """
    next_slot = 0
    prev: Optional[np.ndarray] = None
    for src_idx, (rgb, _pos) in enumerate(frames):
        slot = resample_slots(src_idx, src_fps, dst_fps)
        # slots strictly before this frame's slot belong to the previous frame
        while prev is not None and next_slot < slot:
            yield prev.copy(), (next_slot + 1) / dst_fps * 1000.0
            next_slot += 1
        prev = rgb  # claims slot max(slot, next_slot) unless a later frame does
    if prev is not None:
        yield prev.copy(), (next_slot + 1) / dst_fps * 1000.0


def open_video(
    video_path: str,
    extraction_fps: Optional[int] = None,
    tmp_path: str = "./tmp",
    keep_tmp_files: bool = False,
    use_ffmpeg: str = "auto",
    transform: Optional[Callable[[np.ndarray], np.ndarray]] = None,
    retries: int = 2,
    retry_backoff: float = 0.5,
) -> Tuple[VideoMeta, Iterator[Tuple[np.ndarray, float]]]:
    """Open a video; return (meta, iterator of (rgb_uint8_frame, pos_msec)).

    ``extraction_fps`` changes the effective frame rate: via ffmpeg re-encode when
    available (``use_ffmpeg='auto'``/'always'; exact reference parity) or via the
    native sampler ('never' or no ffmpeg binary). ``transform``, if given, is applied
    to each RGB frame on the host (e.g. PIL-bilinear resize).

    Failed ffmpeg re-encodes (transient: :class:`FfmpegError`) are retried
    ``retries`` times with exponential backoff starting at ``retry_backoff``
    seconds; if every attempt fails under ``use_ffmpeg='auto'``, the native
    sampler takes over (graceful degradation — the video survives at the cost
    of sampler-vs-reencode parity) while 'always' propagates the error.
    Unopenable/corrupt containers raise a classified :class:`DecodeError`.
    """
    if use_ffmpeg not in ("auto", "always", "never"):
        raise ValueError(f"use_ffmpeg must be 'auto'|'always'|'never', got {use_ffmpeg!r}")
    if not os.path.exists(video_path):
        raise FileNotFoundError(f"video does not exist: {video_path}")
    fault_point("decode", video_path)
    reencoded = None
    if extraction_fps is not None and use_ffmpeg != "never":
        if ffmpeg_io.have_ffmpeg():
            try:
                reencoded = retry_call(
                    lambda: ffmpeg_io.reencode_video_with_diff_fps(
                        video_path, tmp_path, extraction_fps
                    ),
                    RetryPolicy(attempts=retries + 1, base_delay=retry_backoff),
                )
                video_path = reencoded
            except FfmpegError as e:
                if use_ffmpeg == "always":
                    # the bounded retry above already owns this transient
                    # class; mark the escaping instance permanent so the
                    # per-video retry layer does not multiply the attempts
                    # (retries+1)^2-fold
                    e.transient = False
                    raise
                print(
                    f"warning: ffmpeg re-encode failed for {video_path} "
                    f"({e}); falling back to the native fps sampler",
                    file=sys.stderr,
                )
        elif use_ffmpeg == "always":
            raise RuntimeError(
                "use_ffmpeg='always' requested for fps resampling but ffmpeg is not "
                "installed; use use_ffmpeg='auto' to fall back to the native sampler"
            )

    cap = cv2.VideoCapture(video_path)
    if not cap.isOpened():
        cap.release()
        raise DecodeError(f"{video_path}: cannot open container (corrupt or unsupported)")
    src_fps = cap.get(cv2.CAP_PROP_FPS)
    src_count = int(cap.get(cv2.CAP_PROP_FRAME_COUNT))
    native_resample = extraction_fps is not None and reencoded is None
    if native_resample:
        if src_fps <= 0:
            cap.release()
            raise DecodeError(
                f"{video_path}: container reports fps={src_fps}; cannot resample to "
                f"{extraction_fps} fps without a source rate"
            )
        # approximate post-resampling frame count (same duration, new rate)
        out_count = int(round(src_count * float(extraction_fps) / src_fps)) if src_count > 0 else 0
    else:
        out_count = src_count
    meta = VideoMeta(
        path=video_path,
        fps=float(extraction_fps) if extraction_fps is not None else src_fps,
        frame_count=out_count,
        width=int(cap.get(cv2.CAP_PROP_FRAME_WIDTH)),
        height=int(cap.get(cv2.CAP_PROP_FRAME_HEIGHT)),
    )

    if native_resample:
        frames = _resampled_frames(_raw_frames(cap), src_fps, float(extraction_fps))
    else:
        frames = _raw_frames(cap)

    def _iter():
        try:
            for rgb, pos in frames:
                if transform is not None:
                    rgb = transform(rgb)
                yield rgb, pos
        finally:
            cap.release()
            if reencoded is not None and not keep_tmp_files and os.path.exists(reencoded):
                os.remove(reencoded)

    return meta, _iter()


# ---------------------------------------------------------------------------
# Segmented intra-video decode
#
# A long video decoded as ONE sequential cv2 stream caps throughput at
# single-stream decode speed even when the rest of the decode pool idles.
# plan_segments() splits the source frame range into seek-aligned segments;
# open_video_segment() decodes one segment frame-exact so the concatenation of
# all segments is byte-identical to open_video()'s sequential stream — both the
# raw path and the native fps-resample path (per-segment slot boundaries are
# pure arithmetic over resample_slots, so resample state never crosses a
# segment boundary). The ffmpeg RE-ENCODE resample path is never segmented:
# it decodes a different (re-encoded) container whose parity anchor is the
# sequential re-encode itself.
# ---------------------------------------------------------------------------


@dataclass
class SegmentPlan:
    """A seek-aligned split of one video into concurrently decodable segments.

    ``meta`` is the whole-video output meta (identical to what
    :func:`open_video` would return for the same knobs); ``bounds`` partitions
    the SOURCE frame index range ``[0, source_meta.frame_count)``.
    """

    source_meta: VideoMeta
    meta: VideoMeta
    extraction_fps: Optional[float]
    min_segment_frames: int
    bounds: List[Tuple[int, int]] = field(default_factory=list)

    def narrow(self, max_segments: int) -> Optional["SegmentPlan"]:
        """Re-slice for fewer permits than originally planned (or None)."""
        return plan_segments(
            self.source_meta, max_segments,
            extraction_fps=self.extraction_fps,
            min_segment_frames=self.min_segment_frames,
        )


def plan_segments(
    meta: VideoMeta,
    max_segments: int,
    extraction_fps: Optional[float] = None,
    min_segment_frames: int = 2,
) -> Optional[SegmentPlan]:
    """Split ``meta``'s frame range into ≤ ``max_segments`` near-equal segments.

    Returns None when the video is too short to split (every segment must hold
    at least ``min_segment_frames`` source frames) or the header metadata is
    too degenerate to seek against. The header ``frame_count`` may undercount
    (the final segment reads to EOF and absorbs the surplus); a header that
    OVERcounts fails the video with a classified stitch error at decode time —
    the per-video fault barrier catches it like any other decode failure.
    """
    total = meta.frame_count
    if total <= 0 or meta.fps <= 0 or meta.width <= 0 or meta.height <= 0:
        return None
    k = min(max_segments, total // max(1, min_segment_frames))
    if k < 2:
        return None
    bounds = []
    for j in range(k):
        start = total * j // k
        end = total * (j + 1) // k
        bounds.append((start, end))
    if extraction_fps is not None:
        out_count = int(round(total * float(extraction_fps) / meta.fps))
        out_fps = float(extraction_fps)
    else:
        out_count = total
        out_fps = meta.fps
    out_meta = VideoMeta(path=meta.path, fps=out_fps, frame_count=out_count,
                         width=meta.width, height=meta.height)
    return SegmentPlan(source_meta=meta, meta=out_meta,
                       extraction_fps=(float(extraction_fps)
                                       if extraction_fps is not None else None),
                       min_segment_frames=min_segment_frames, bounds=bounds)


def _seeked_capture(path: str, start: int) -> Tuple[Optional[cv2.VideoCapture], int]:
    """Open ``path`` positioned at/before source frame ``start``.

    Returns ``(cap, lead_in)`` where ``lead_in`` frames must be decoded and
    dropped before the target (keyframe snap), or ``(None, 0)`` when the
    backend's seek overshot or reported garbage — the caller then falls back
    to the ffmpeg fast-seek streamer or an exact decode-and-drop rescan. The
    same decoder as sequential decode produces the segment's pixels, which is
    what makes stitched output byte-identical by construction.
    """
    cap = cv2.VideoCapture(path)
    if not cap.isOpened():
        cap.release()
        raise DecodeError(f"{path}: cannot open container (corrupt or unsupported)")
    if start <= 0:
        return cap, 0
    cap.set(cv2.CAP_PROP_POS_FRAMES, float(start))
    landed = int(cap.get(cv2.CAP_PROP_POS_FRAMES))
    if 0 <= landed <= start:
        # landed == start: frame-exact seek; landed < start: the backend
        # snapped to a seek point (keyframe) — decode the lead-in and drop it
        return cap, start - landed
    cap.release()
    return None, 0


def _segment_source_frames(
    cap: cv2.VideoCapture, lead_in: int, count: Optional[int],
    first_segment: bool, path: str, start: int,
) -> Iterator[Tuple[np.ndarray, float]]:
    """cv2 frames of one segment: drop ``lead_in``, then yield exactly ``count``.

    Segment 0 keeps :func:`_raw_frames`'s one-missing-first-frame tolerance
    (the workaround is a decoder open hiccup, not a content property — it can
    only happen at the true start of the stream); middle segments are strict:
    an early EOF means the container header lied about its frame count, which
    breaks the stitch invariant, so it raises instead of silently yielding a
    short (non-parity) stream. ``count=None`` (final segment) reads to EOF.
    """
    try:
        for _ in range(lead_in):
            ok, _bgr = cap.read()
            if not ok:
                raise DecodeError(
                    f"{path}: EOF during seek lead-in before frame {start} "
                    f"(container frame count unreliable; rerun with "
                    f"--decode_segments 1)"
                )
        got = 0
        first_attempt = first_segment
        while count is None or got < count:
            ok, bgr = cap.read()
            if first_attempt:
                first_attempt = False
                if ok is False:
                    continue
            if not ok:
                if count is not None:
                    raise DecodeError(
                        f"{path}: segment [{start}, {start + count}) underran "
                        f"after {got} frames (container frame count "
                        f"unreliable; rerun with --decode_segments 1)"
                    )
                break
            yield cv2.cvtColor(bgr, cv2.COLOR_BGR2RGB), cap.get(cv2.CAP_PROP_POS_MSEC)
            got += 1
    finally:
        cap.release()


def _segment_resampled(
    frames: Iterator[Tuple[np.ndarray, float]],
    start: int,
    src_fps: float,
    dst_fps: float,
    final_segment: bool,
    end: int,
) -> Iterator[Tuple[np.ndarray, float]]:
    """:func:`_resampled_frames` semantics restricted to source ``[start, end)``.

    The sequential resampler's only cross-frame state entering source index
    ``i`` is ``(next_slot, prev) = (resample_slots(i-1), frame[i-1])`` — and a
    segment's FIRST frame needs no ``prev`` because slots strictly below
    ``resample_slots(start)`` were flushed by the previous segment. Initial
    ``next_slot`` is therefore pure arithmetic. Tail: a middle segment flushes
    its last frame into slots up to ``resample_slots(end)`` (exactly what the
    sequential loop does when processing frame ``end``); the final segment
    emits its last frame ONCE (the sequential EOF flush).
    """
    next_slot = resample_slots(start, src_fps, dst_fps) if start > 0 else 0
    prev: Optional[np.ndarray] = None
    n = 0
    for off, (rgb, _pos) in enumerate(frames):
        slot = resample_slots(start + off, src_fps, dst_fps)
        while prev is not None and next_slot < slot:
            yield prev.copy(), (next_slot + 1) / dst_fps * 1000.0
            next_slot += 1
        prev = rgb
        n += 1
    if prev is None:
        return
    if final_segment:
        yield prev.copy(), (next_slot + 1) / dst_fps * 1000.0
        return
    end_slot = resample_slots(end, src_fps, dst_fps)
    while next_slot < end_slot:
        yield prev.copy(), (next_slot + 1) / dst_fps * 1000.0
        next_slot += 1


def _require_nonempty(
    frames: Iterator[Tuple[np.ndarray, float]], path: str, start: int,
) -> Iterator[Tuple[np.ndarray, float]]:
    """Fail a final segment that finds EOF already behind its start frame.

    Sequential decode would have emitted its EOF flush from an earlier frame;
    a silently empty tail segment would stitch into a non-parity stream, so
    the header overcount is surfaced as a classified stitch error instead.
    """
    n = 0
    for item in frames:
        n += 1
        yield item
    if n == 0:
        raise DecodeError(
            f"{path}: final segment starting at frame {start} found no frames "
            f"(container frame count unreliable; rerun with --decode_segments 1)"
        )


def open_video_segment(
    plan: SegmentPlan,
    index: int,
    transform: Optional[Callable[[np.ndarray], np.ndarray]] = None,
    seek: str = "auto",
) -> Iterator[Tuple[np.ndarray, float]]:
    """Frames of segment ``index`` of ``plan``, stitchable byte-exact.

    Chaining ``open_video_segment(plan, 0) .. open_video_segment(plan, k-1)``
    yields the same (frame, timestamp) stream as sequential
    :func:`open_video` with the same ``extraction_fps``/``transform`` (native
    resample path). Seek backend:

    - ``auto``/``cv2`` — ``CAP_PROP_POS_FRAMES`` seek with readback
      verification; a keyframe snap (landed short) decodes and drops the
      lead-in. Same decoder as sequential decode, so parity holds by
      construction.
    - ``auto`` falls back to the ffmpeg ``-ss`` fast-seek rawvideo streamer
      when cv2's seek overshoots/misreports AND the stream is fps-resampled
      (there timestamps are slot arithmetic; the raw path needs cv2's
      container ``POS_MSEC``), else to an exact decode-and-drop rescan.
    - ``ffmpeg`` forces the streamer for non-first segments; raw-path
      timestamps are then synthesized as ``(i+1)/fps`` — exact for
      constant-frame-rate containers only.
    """
    if seek not in ("auto", "ffmpeg", "cv2"):
        raise ValueError(f"seek must be 'auto'|'ffmpeg'|'cv2', got {seek!r}")
    if not 0 <= index < len(plan.bounds):
        raise ValueError(f"segment index {index} outside plan of {len(plan.bounds)}")
    src = plan.source_meta
    start, end = plan.bounds[index]
    final_segment = index == len(plan.bounds) - 1
    count = None if final_segment else end - start
    fault_point("decode_segment", f"{src.path}#seg{index}")

    use_ffmpeg_seek = seek == "ffmpeg" and start > 0
    raw: Optional[Iterator[Tuple[np.ndarray, float]]] = None
    if not use_ffmpeg_seek:
        cap, lead_in = _seeked_capture(src.path, start)
        if cap is None:
            # cv2 cannot land on this container; resampled streams ignore the
            # container timestamp, so ffmpeg's fast seek is safe there
            if plan.extraction_fps is not None and ffmpeg_io.have_ffmpeg() and seek == "auto":
                use_ffmpeg_seek = True
            else:
                cap = cv2.VideoCapture(src.path)
                if not cap.isOpened():
                    cap.release()
                    raise DecodeError(
                        f"{src.path}: cannot open container (corrupt or unsupported)")
                lead_in = start  # exact O(start) decode-and-drop rescan
        if cap is not None:
            raw = _segment_source_frames(cap, lead_in, count, index == 0,
                                         src.path, start)
    if use_ffmpeg_seek:
        stream = ffmpeg_io.segment_frames(
            src.path, start, count, src.fps, src.width, src.height)
        raw = ((rgb, (start + off + 1) / src.fps * 1000.0)
               for off, rgb in enumerate(stream))

    if final_segment and start > 0:
        raw = _require_nonempty(raw, src.path, start)
    if plan.extraction_fps is not None:
        frames = _segment_resampled(raw, start, src.fps, plan.extraction_fps,
                                    final_segment, end)
    else:
        frames = raw
    if transform is None:
        return frames
    return ((transform(rgb), pos) for rgb, pos in frames)


def decode_all(video_path: str, **kw) -> Tuple[VideoMeta, np.ndarray, np.ndarray]:
    """Decode the whole video into (meta, frames (T,H,W,C) uint8, timestamps_ms (T,)).

    Whole-video decode is the R(2+1)D path (reference uses
    ``torchvision.io.read_video``, ``extract_r21d.py:102``); other models stream.
    """
    meta, frames = open_video(video_path, **kw)
    out, ts = [], []
    for rgb, pos in frames:
        out.append(rgb)
        ts.append(pos)
    if not out:
        h, w = max(meta.height, 0), max(meta.width, 0)
        return meta, np.zeros((0, h, w, 3), np.uint8), np.zeros((0,))
    return meta, np.stack(out), np.asarray(ts)
