"""ffmpeg subprocess shims (re-encode to a target fps, mp4→wav audio extraction).

Mirrors ``which_ffmpeg`` / ``reencode_video_with_diff_fps`` / ``extract_wav_from_mp4``
(``utils/utils.py:136-201``). ffmpeg is an optional host-side dependency here: when the
binary is absent, fps changes fall back to index-based frame sampling in the decoder
(:mod:`video_features_tpu.io.video`), and mp4 audio extraction raises a clear error
(wav inputs still work via scipy).

Subprocess failures raise :class:`~..reliability.FfmpegError` (transient — dead
children are usually environmental: OOM killer, tmp-dir pressure) instead of the
reference's fire-and-forget ``subprocess.call`` whose nonzero exits were silently
ignored and surfaced later as empty decode streams.
"""

from __future__ import annotations

import hashlib
import os
import pathlib
import shutil
import subprocess
from typing import Iterator, Optional, Sequence, Tuple

import numpy as np

from ..reliability import FfmpegError, fault_point


def which_ffmpeg() -> str:
    """Path to ffmpeg, or '' when not installed (reference ``utils/utils.py:136-144``)."""
    return shutil.which("ffmpeg") or ""


def have_ffmpeg() -> bool:
    return which_ffmpeg() != ""


# stderr markers for failures caused by the INPUT BYTES, which no amount of
# retrying will change — these demote the (class-transient) FfmpegError to
# permanent so the retry budget is spent on environmental deaths only
_PERMANENT_STDERR_MARKERS = (
    "Invalid data found when processing input",
    "moov atom not found",
    "does not contain any stream",
)


def _run_checked(cmd: Sequence[str], src_path: str, out_path: str) -> None:
    """Run one ffmpeg command; classify every way it can fail.

    The reference's ``subprocess.call`` discards the return code, so a crashed
    or killed ffmpeg surfaced only as a missing/empty output file decoded into
    zero frames downstream. Here: nonzero exit, a spawn failure, and a
    missing/empty output all raise :class:`FfmpegError` naming the source.
    Input-caused exits (corrupt container, no audio stream) are tagged
    permanent; environmental deaths (signals, spawn failures) stay transient.
    """
    fault_point("ffmpeg", src_path)
    try:
        proc = subprocess.run(cmd, capture_output=True, text=True)
    except OSError as e:
        raise FfmpegError(f"could not spawn ffmpeg for {src_path}: {e}") from e
    if proc.returncode != 0:
        raise _classified_exit_error(proc.returncode, proc.stderr or "", src_path)
    if not os.path.exists(out_path) or os.path.getsize(out_path) == 0:
        raise FfmpegError(f"ffmpeg exited 0 but produced no output at {out_path}")


def _classified_exit_error(returncode: int, stderr: str, src_path: str) -> FfmpegError:
    """Nonzero-exit taxonomy shared by the batch runner and the segment streamer."""
    stderr = stderr.strip()
    tail = stderr.splitlines()[-3:]
    err = FfmpegError(
        f"ffmpeg exited {returncode} for {src_path}"
        + (": " + " | ".join(tail) if tail else "")
    )
    if returncode > 0 and any(m in stderr for m in _PERMANENT_STDERR_MARKERS):
        err.transient = False  # the bytes will not improve; do not retry
    return err


def segment_frames(
    video_path: str,
    start_frame: int,
    frame_count: Optional[int],
    fps: float,
    width: int,
    height: int,
) -> Iterator[np.ndarray]:
    """Fast-seek decode of frames ``[start_frame, start_frame+frame_count)`` as RGB.

    ``-ss`` placed BEFORE ``-i`` is ffmpeg's fast seek: the demuxer jumps to the
    nearest seek point (keyframe) at or before the target timestamp, then the
    decoder drops the lead-in frames between that keyframe and the target
    (``accurate_seek``, on by default) — so landing is frame-exact without
    decoding the whole prefix. Seeking to half a frame interval before the
    target frame's pts keeps rounding from swallowing the target frame itself
    on constant-frame-rate streams. ``frame_count=None`` reads to EOF.

    Yields ``(height, width, 3)`` uint8 RGB arrays streamed off a rawvideo
    pipe (no disk round-trip). Failures raise :class:`FfmpegError` with the
    same input-vs-environment taxonomy as the re-encode path.
    """
    if not have_ffmpeg():
        raise RuntimeError(
            "ffmpeg is not installed; segment decode must use the cv2 seek "
            "backend on this host (segment_seek='cv2' or 'auto')"
        )
    if fps <= 0:
        raise ValueError(f"segment_frames needs a positive fps, got {fps}")
    fault_point("ffmpeg", video_path)
    cmd = [which_ffmpeg(), "-hide_banner", "-loglevel", "error", "-nostdin"]
    if start_frame > 0:
        cmd += ["-ss", f"{max(0.0, (start_frame - 0.5) / fps):.6f}"]
    cmd += ["-i", video_path]
    if frame_count is not None:
        cmd += ["-frames:v", str(frame_count)]
    cmd += ["-f", "rawvideo", "-pix_fmt", "rgb24", "pipe:1"]
    frame_bytes = width * height * 3
    try:
        proc = subprocess.Popen(
            cmd, stdout=subprocess.PIPE, stderr=subprocess.PIPE)
    except OSError as e:
        raise FfmpegError(f"could not spawn ffmpeg for {video_path}: {e}") from e
    try:
        got = 0
        while frame_count is None or got < frame_count:
            buf = proc.stdout.read(frame_bytes)
            while 0 < len(buf) < frame_bytes:
                chunk = proc.stdout.read(frame_bytes - len(buf))
                if not chunk:
                    break
                buf += chunk
            if len(buf) < frame_bytes:
                # short read: EOF (fine when streaming to EOF) or a dead child
                stderr = proc.stderr.read().decode(errors="replace")
                rc = proc.wait()
                if rc != 0:
                    raise _classified_exit_error(rc, stderr, video_path)
                if frame_count is not None:
                    raise FfmpegError(
                        f"{video_path}: segment [{start_frame}, "
                        f"{start_frame + frame_count}) underran after {got} "
                        f"frames (container frame count unreliable; rerun "
                        f"with --decode_segments 1)"
                    )
                return
            yield np.frombuffer(buf, np.uint8).reshape(height, width, 3)
            got += 1
    finally:
        if proc.poll() is None:
            proc.kill()
        proc.stdout.close()
        proc.stderr.close()
        proc.wait()


def reencode_video_with_diff_fps(video_path: str, tmp_path: str, extraction_fps: int) -> str:
    """Re-encode ``video_path`` at ``extraction_fps`` into ``tmp_path``; return new path.

    Matches ``utils/utils.py:147-169`` behavior; the tmp name extends the
    reference's ``<stem>_new_fps.mp4`` with a short source-path hash — two
    same-basename videos from different directories (decoded concurrently by
    ``--decode_workers``, or sequentially with ``keep_tmp_files``) must not
    share one tmp file (ffmpeg runs with ``-y``: the second would overwrite
    the first mid-read).
    """
    if not have_ffmpeg():
        raise RuntimeError(
            "ffmpeg is not installed; use the decoder's native fps resampling "
            "(io.video.open_video(..., extraction_fps=..., use_ffmpeg='never')) instead"
        )
    if not video_path.endswith(".mp4"):
        raise ValueError("The file does not end with .mp4")
    os.makedirs(tmp_path, exist_ok=True)
    tag = hashlib.md5(os.path.abspath(video_path).encode()).hexdigest()[:8]
    new_path = os.path.join(
        tmp_path, f"{pathlib.Path(video_path).stem}_{tag}_new_fps.mp4")
    cmd = [
        which_ffmpeg(), "-hide_banner", "-loglevel", "error", "-y",
        "-i", video_path, "-filter:v", f"fps=fps={extraction_fps}", new_path,
    ]
    _run_checked(cmd, video_path, new_path)
    return new_path


def extract_wav_from_mp4(video_path: str, tmp_path: str) -> Tuple[str, str]:
    """mp4 → aac → wav via two ffmpeg calls (reference ``utils/utils.py:172-201``).

    Returns (wav_path, aac_path); both land in ``tmp_path`` for ``keep_tmp_files``.
    """
    if not have_ffmpeg():
        raise RuntimeError(
            "ffmpeg is not installed; VGGish can only consume .wav inputs directly "
            "on this host (pass paths ending in .wav)"
        )
    if not video_path.endswith(".mp4"):
        raise ValueError("The file does not end with .mp4")
    os.makedirs(tmp_path, exist_ok=True)
    stem = pathlib.Path(video_path).stem
    aac_path = os.path.join(tmp_path, f"{stem}.aac")
    wav_path = os.path.join(tmp_path, f"{stem}.wav")
    _run_checked([
        which_ffmpeg(), "-hide_banner", "-loglevel", "error", "-y",
        "-i", video_path, "-acodec", "copy", aac_path,
    ], video_path, aac_path)
    try:
        _run_checked([
            which_ffmpeg(), "-hide_banner", "-loglevel", "error", "-y",
            "-i", aac_path, wav_path,
        ], aac_path, wav_path)
    except FfmpegError:
        # the caller's cleanup never sees (wav, aac) when this raises — don't
        # leak one orphaned .aac per terminally-failed video into tmp_path
        try:
            if os.path.exists(aac_path):
                os.remove(aac_path)
        except OSError:
            pass
        raise
    return wav_path, aac_path
