"""ffmpeg subprocess shims (re-encode to a target fps, mp4→wav audio extraction).

Mirrors ``which_ffmpeg`` / ``reencode_video_with_diff_fps`` / ``extract_wav_from_mp4``
(``utils/utils.py:136-201``). ffmpeg is an optional host-side dependency here: when the
binary is absent, fps changes fall back to index-based frame sampling in the decoder
(:mod:`video_features_tpu.io.video`), and mp4 audio extraction raises a clear error
(wav inputs still work via scipy).
"""

from __future__ import annotations

import hashlib
import os
import pathlib
import shutil
import subprocess
from typing import Tuple


def which_ffmpeg() -> str:
    """Path to ffmpeg, or '' when not installed (reference ``utils/utils.py:136-144``)."""
    return shutil.which("ffmpeg") or ""


def have_ffmpeg() -> bool:
    return which_ffmpeg() != ""


def reencode_video_with_diff_fps(video_path: str, tmp_path: str, extraction_fps: int) -> str:
    """Re-encode ``video_path`` at ``extraction_fps`` into ``tmp_path``; return new path.

    Matches ``utils/utils.py:147-169`` behavior; the tmp name extends the
    reference's ``<stem>_new_fps.mp4`` with a short source-path hash — two
    same-basename videos from different directories (decoded concurrently by
    ``--decode_workers``, or sequentially with ``keep_tmp_files``) must not
    share one tmp file (ffmpeg runs with ``-y``: the second would overwrite
    the first mid-read).
    """
    if not have_ffmpeg():
        raise RuntimeError(
            "ffmpeg is not installed; use the decoder's native fps resampling "
            "(io.video.open_video(..., extraction_fps=..., use_ffmpeg='never')) instead"
        )
    if not video_path.endswith(".mp4"):
        raise ValueError("The file does not end with .mp4")
    os.makedirs(tmp_path, exist_ok=True)
    tag = hashlib.md5(os.path.abspath(video_path).encode()).hexdigest()[:8]
    new_path = os.path.join(
        tmp_path, f"{pathlib.Path(video_path).stem}_{tag}_new_fps.mp4")
    cmd = [
        which_ffmpeg(), "-hide_banner", "-loglevel", "panic", "-y",
        "-i", video_path, "-filter:v", f"fps=fps={extraction_fps}", new_path,
    ]
    subprocess.call(cmd)
    return new_path


def extract_wav_from_mp4(video_path: str, tmp_path: str) -> Tuple[str, str]:
    """mp4 → aac → wav via two ffmpeg calls (reference ``utils/utils.py:172-201``).

    Returns (wav_path, aac_path); both land in ``tmp_path`` for ``keep_tmp_files``.
    """
    if not have_ffmpeg():
        raise RuntimeError(
            "ffmpeg is not installed; VGGish can only consume .wav inputs directly "
            "on this host (pass paths ending in .wav)"
        )
    if not video_path.endswith(".mp4"):
        raise ValueError("The file does not end with .mp4")
    os.makedirs(tmp_path, exist_ok=True)
    stem = pathlib.Path(video_path).stem
    aac_path = os.path.join(tmp_path, f"{stem}.aac")
    wav_path = os.path.join(tmp_path, f"{stem}.wav")
    subprocess.call([
        which_ffmpeg(), "-hide_banner", "-loglevel", "panic", "-y",
        "-i", video_path, "-acodec", "copy", aac_path,
    ])
    subprocess.call([
        which_ffmpeg(), "-hide_banner", "-loglevel", "panic", "-y",
        "-i", aac_path, wav_path,
    ])
    return wav_path, aac_path
