"""Forming and sharding the list of input videos.

Reproduces the semantics of ``form_list_from_user_input`` (``utils/utils.py:108-133``)
and the round-robin job sharder ``gen_file_list.py:6-21`` of the reference. Sharding is
also the multi-host data-parallel axis: each host takes ``shard(paths, host_id,
num_hosts)`` and processes it independently (videos are embarrassingly parallel —
SURVEY.md §2.3).
"""

from __future__ import annotations

import os
from typing import List, Optional, Sequence


def form_video_list(
    video_paths: Sequence[str] = (),
    file_with_video_paths: Optional[str] = None,
    warn_missing: bool = True,
) -> List[str]:
    """Return the list of video paths from either an explicit list or a .txt file.

    A file wins over the explicit list (reference behavior, ``utils/utils.py:118-125``);
    blank lines are dropped; missing paths are reported but kept (the per-video fault
    barrier downstream will skip them).
    """
    if file_with_video_paths is not None:
        with open(file_with_video_paths) as rfile:
            path_list = [line.strip("\n") for line in rfile]
        path_list = [p for p in path_list if p]
    else:
        path_list = list(video_paths)

    if warn_missing:
        for path in path_list:
            if not os.path.exists(path):
                print(f"The path does not exist: {path}")
    return path_list


def shard_round_robin(paths: Sequence[str], shard_id: int, num_shards: int) -> List[str]:
    """Round-robin shard of the path list (reference ``gen_file_list.py:6-13``).

    Used both for generating N job files and for multi-host DCN sharding: host k of N
    processes ``shard_round_robin(paths, k, N)``.
    """
    if not 0 <= shard_id < num_shards:
        raise ValueError(f"shard_id {shard_id} out of range for {num_shards} shards")
    return [p for i, p in enumerate(paths) if i % num_shards == shard_id]


def write_shard_files(
    video_dir: str, output_dir: str, num_shards: int, prefix: str = "file_list"
) -> List[str]:
    """Write N round-robin shard .txt files for launching N independent jobs.

    Equivalent of the reference's ``gen_file_list.py`` helper script.
    """
    paths = sorted(
        os.path.join(video_dir, name)
        for name in os.listdir(video_dir)
        if not name.startswith(".")
    )
    os.makedirs(output_dir, exist_ok=True)
    out_files = []
    for shard_id in range(num_shards):
        shard = shard_round_robin(paths, shard_id, num_shards)
        out_path = os.path.join(output_dir, f"{prefix}_{shard_id}.txt")
        with open(out_path, "w") as f:
            f.write("".join(p + "\n" for p in shard))
        out_files.append(out_path)
    return out_files
