"""Output actions: what happens to a finished feature dict.

Reproduces ``action_on_extraction`` (``utils/utils.py:45-74``) including the
``<stem>_<key>.npy`` naming and the per-feature-type output subdirectory the reference
extractors join before calling it (e.g. ``extract_i3d.py:78``). Adds a done-manifest so
interrupted jobs can resume (the reference reruns everything — SURVEY.md §5).

Writes are atomic (tmp + ``os.replace``): a SIGKILL mid-save must never leave a
truncated ``.npy`` that a later ``--resume`` counts as done. Filesystem failures
raise :class:`~..reliability.OutputError` (transient — disk/NFS pressure clears).
"""

from __future__ import annotations

import json
import os
import pathlib
import sys
from typing import Dict, Mapping

import numpy as np

from ..reliability import OutputError, fault_point
from ..reliability.manifest import read_jsonl

MANIFEST_NAME = ".done_manifest.jsonl"


def feature_output_dir(output_path: str, feature_type: str) -> str:
    """Features land in ``<output_path>/<feature_type>/`` (reference extract_*.py)."""
    return os.path.join(output_path, feature_type)


def _atomic_save(fpath: str, value: np.ndarray) -> None:
    """Write ``value`` to ``fpath`` via tmp + rename; never a truncated final file.

    ``np.save`` appends ``.npy`` to *names*, not file objects, so the tmp file
    is written through an explicit handle. A crash between write and rename
    leaves only ``<file>.npy.tmp`` — invisible to loaders and to ``--resume``.
    """
    tmp = fpath + ".tmp"
    try:
        with open(tmp, "wb") as f:
            np.save(f, value)
        fault_point("save", fpath)
        os.replace(tmp, fpath)
    except OSError as e:
        raise OutputError(f"failed to write {fpath}: {e}") from e
    finally:
        # on success the replace consumed tmp; on ANY failure (including an
        # injected fault) remove it — only a hard kill may leave one behind
        if os.path.exists(tmp):
            try:
                os.remove(tmp)
            except OSError:
                pass


def action_on_extraction(
    feats_dict: Mapping[str, np.ndarray],
    video_path: str,
    output_path: str,
    on_extraction: str = "print",
) -> Dict[str, str]:
    """Print or save each array in ``feats_dict``.

    ``print`` dumps the array plus a ``max/mean/min`` stats line (the reference's
    numeric smoke test, ``utils/utils.py:57-61``); ``save_numpy`` writes
    ``<stem>_<key>.npy`` under ``output_path``. Returns ``{key: saved_path}`` for
    ``save_numpy`` (empty for ``print``).
    """
    saved: Dict[str, str] = {}
    for key, value in feats_dict.items():
        value = np.asarray(value)
        if on_extraction == "print":
            print(key)
            print(value)
            print(f"max: {value.max():.8f}; mean: {value.mean():.8f}; min: {value.min():.8f}")
            print()
        elif on_extraction == "save_numpy":
            try:
                os.makedirs(output_path, exist_ok=True)
            except OSError as e:
                raise OutputError(f"cannot create output dir {output_path}: {e}") from e
            fname = f"{pathlib.Path(video_path).stem}_{key}.npy"
            fpath = os.path.join(output_path, fname)
            if value.ndim > 0 and len(value) == 0:
                print(f"Warning: the value is empty for {key} @ {fpath}")
            _atomic_save(fpath, value)
            saved[key] = fpath
        else:
            raise NotImplementedError(f"on_extraction: {on_extraction} is not implemented")
    return saved


def manifest_path(output_path: str) -> str:
    return os.path.join(output_path, MANIFEST_NAME)


def mark_done(output_path: str, video_path: str, keys) -> None:
    """Append a completion record for ``video_path`` to the done-manifest."""
    record = {"video": os.path.abspath(video_path), "keys": sorted(keys)}
    try:
        os.makedirs(output_path, exist_ok=True)
        with open(manifest_path(output_path), "a") as f:
            f.write(json.dumps(record) + "\n")
    except OSError as e:
        raise OutputError(f"cannot append to done-manifest in {output_path}: {e}") from e


def load_done_set(output_path: str) -> set:
    """Absolute video paths already completed according to the manifest.

    Corrupt/undecodable lines (a crash mid-append, manual edits) are counted
    and warned about, not silently skipped: every dropped line is a video that
    ``--resume`` will re-extract, and the operator should know why.
    """
    done = set()
    path = manifest_path(output_path)
    records, corrupt = read_jsonl(path)
    for record in records:
        if "video" in record:
            done.add(record["video"])
        else:
            corrupt += 1
    if corrupt:
        print(
            f"warning: ignored {corrupt} corrupt line(s) in {path}; "
            "the affected videos will be re-extracted on --resume",
            file=sys.stderr,
        )
    return done
