"""Output actions: what happens to a finished feature dict.

Reproduces ``action_on_extraction`` (``utils/utils.py:45-74``) including the
``<stem>_<key>.npy`` naming and the per-feature-type output subdirectory the reference
extractors join before calling it (e.g. ``extract_i3d.py:78``). Adds a done-manifest so
interrupted jobs can resume (the reference reruns everything — SURVEY.md §5).
"""

from __future__ import annotations

import json
import os
import pathlib
from typing import Dict, Mapping

import numpy as np

MANIFEST_NAME = ".done_manifest.jsonl"


def feature_output_dir(output_path: str, feature_type: str) -> str:
    """Features land in ``<output_path>/<feature_type>/`` (reference extract_*.py)."""
    return os.path.join(output_path, feature_type)


def action_on_extraction(
    feats_dict: Mapping[str, np.ndarray],
    video_path: str,
    output_path: str,
    on_extraction: str = "print",
) -> Dict[str, str]:
    """Print or save each array in ``feats_dict``.

    ``print`` dumps the array plus a ``max/mean/min`` stats line (the reference's
    numeric smoke test, ``utils/utils.py:57-61``); ``save_numpy`` writes
    ``<stem>_<key>.npy`` under ``output_path``. Returns ``{key: saved_path}`` for
    ``save_numpy`` (empty for ``print``).
    """
    saved: Dict[str, str] = {}
    for key, value in feats_dict.items():
        value = np.asarray(value)
        if on_extraction == "print":
            print(key)
            print(value)
            print(f"max: {value.max():.8f}; mean: {value.mean():.8f}; min: {value.min():.8f}")
            print()
        elif on_extraction == "save_numpy":
            os.makedirs(output_path, exist_ok=True)
            fname = f"{pathlib.Path(video_path).stem}_{key}.npy"
            fpath = os.path.join(output_path, fname)
            if value.ndim > 0 and len(value) == 0:
                print(f"Warning: the value is empty for {key} @ {fpath}")
            np.save(fpath, value)
            saved[key] = fpath
        else:
            raise NotImplementedError(f"on_extraction: {on_extraction} is not implemented")
    return saved


def manifest_path(output_path: str) -> str:
    return os.path.join(output_path, MANIFEST_NAME)


def mark_done(output_path: str, video_path: str, keys) -> None:
    """Append a completion record for ``video_path`` to the done-manifest."""
    os.makedirs(output_path, exist_ok=True)
    record = {"video": os.path.abspath(video_path), "keys": sorted(keys)}
    with open(manifest_path(output_path), "a") as f:
        f.write(json.dumps(record) + "\n")


def load_done_set(output_path: str) -> set:
    """Absolute video paths already completed according to the manifest."""
    done = set()
    path = manifest_path(output_path)
    if os.path.exists(path):
        with open(path) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    done.add(json.loads(line)["video"])
                except (json.JSONDecodeError, KeyError):
                    continue
    return done
