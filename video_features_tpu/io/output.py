"""Output actions: what happens to a finished feature dict.

Reproduces ``action_on_extraction`` (``utils/utils.py:45-74``) including the
``<stem>_<key>.npy`` naming and the per-feature-type output subdirectory the reference
extractors join before calling it (e.g. ``extract_i3d.py:78``). Adds a done-manifest so
interrupted jobs can resume (the reference reruns everything — SURVEY.md §5).

Writes are atomic (tmp + ``os.replace``): a SIGKILL mid-save must never leave a
truncated ``.npy`` that a later ``--resume`` counts as done. Filesystem failures
raise :class:`~..reliability.OutputError` (transient — disk/NFS pressure clears).

:class:`AsyncOutputWriter` (default, ``--sync_writer`` reverts) moves the
save + mark-done pair onto a bounded single-writer thread so serialization
overlaps the next video's compute; ordering and atomicity are unchanged.
"""

from __future__ import annotations

import json
import os
import pathlib
import queue
import sys
import threading
from typing import Dict, Mapping, Optional

import numpy as np

from ..reliability import OutputError, VideoTimeoutError, fault_point
from ..reliability.retry import RetryPolicy, retry_call
from ..reliability.manifest import read_jsonl

MANIFEST_NAME = ".done_manifest.jsonl"


def feature_output_dir(output_path: str, feature_type: str) -> str:
    """Features land in ``<output_path>/<feature_type>/`` (reference extract_*.py)."""
    return os.path.join(output_path, feature_type)


def atomic_write_bytes(path: str, data: bytes) -> None:
    """Publish ``data`` at ``path`` via tmp + ``os.replace`` — the shared
    crash-safety discipline (:func:`_atomic_save`, request results, the
    feature cache's CAS entries in ``cache/store.py``): a kill at any point
    leaves either no visible file or a complete one. Raises
    :class:`~..reliability.OutputError` on filesystem failure."""
    tmp = path + ".tmp"
    try:
        with open(tmp, "wb") as f:
            f.write(data)
        os.replace(tmp, path)
    except OSError as e:
        raise OutputError(f"failed to write {path}: {e}") from e
    finally:
        if os.path.exists(tmp):
            try:
                os.remove(tmp)
            except OSError:
                pass


def _atomic_save(fpath: str, value: np.ndarray) -> None:
    """Write ``value`` to ``fpath`` via tmp + rename; never a truncated final file.

    ``np.save`` appends ``.npy`` to *names*, not file objects, so the tmp file
    is written through an explicit handle. A crash between write and rename
    leaves only ``<file>.npy.tmp`` — invisible to loaders and to ``--resume``.
    """
    tmp = fpath + ".tmp"
    try:
        with open(tmp, "wb") as f:
            np.save(f, value)
        fault_point("save", fpath)
        os.replace(tmp, fpath)
    except OSError as e:
        raise OutputError(f"failed to write {fpath}: {e}") from e
    finally:
        # on success the replace consumed tmp; on ANY failure (including an
        # injected fault) remove it — only a hard kill may leave one behind
        if os.path.exists(tmp):
            try:
                os.remove(tmp)
            except OSError:
                pass


def action_on_extraction(
    feats_dict: Mapping[str, np.ndarray],
    video_path: str,
    output_path: str,
    on_extraction: str = "print",
) -> Dict[str, str]:
    """Print or save each array in ``feats_dict``.

    ``print`` dumps the array plus a ``max/mean/min`` stats line (the reference's
    numeric smoke test, ``utils/utils.py:57-61``); ``save_numpy`` writes
    ``<stem>_<key>.npy`` under ``output_path``. Returns ``{key: saved_path}`` for
    ``save_numpy`` (empty for ``print``).
    """
    saved: Dict[str, str] = {}
    for key, value in feats_dict.items():
        value = np.asarray(value)
        if on_extraction == "print":
            print(key)
            print(value)
            print(f"max: {value.max():.8f}; mean: {value.mean():.8f}; min: {value.min():.8f}")
            print()
        elif on_extraction == "save_numpy":
            try:
                os.makedirs(output_path, exist_ok=True)
            except OSError as e:
                raise OutputError(f"cannot create output dir {output_path}: {e}") from e
            fname = f"{pathlib.Path(video_path).stem}_{key}.npy"
            fpath = os.path.join(output_path, fname)
            if value.ndim > 0 and len(value) == 0:
                print(f"Warning: the value is empty for {key} @ {fpath}")
            _atomic_save(fpath, value)
            saved[key] = fpath
        else:
            raise NotImplementedError(f"on_extraction: {on_extraction} is not implemented")
    return saved


def write_outputs(feats_dict: Mapping[str, np.ndarray], video_path: str,
                  output_path: str, on_extraction: str = "save_numpy",
                  cancelled: Optional[threading.Event] = None) -> None:
    """One video's complete output sequence — THE single implementation of
    the write-before-done / cancellation contract, shared by the inline
    path (:meth:`Extractor._process_one`) and the async writer thread:

    1. re-check the watchdog cancel event before touching disk;
    2. ``action_on_extraction`` (atomic per-array tmp+rename saves);
    3. re-check the cancel event — features may exist, but a cancelled
       attempt must NOT be marked done;
    4. append the done-manifest record.
    """

    def check_cancelled(stage: str) -> None:
        if cancelled is not None and cancelled.is_set():
            raise VideoTimeoutError(
                f"{video_path}: attempt was cancelled by the watchdog; {stage}")

    check_cancelled("discarding features before any write")
    action_on_extraction(feats_dict, video_path, output_path, on_extraction)
    if on_extraction == "save_numpy":
        # write-before-done ordering: the record lands only after every
        # .npy of this video has been atomically renamed into place
        check_cancelled("features written but NOT marked done")
        mark_done(output_path, video_path, feats_dict.keys())


class FeatureAssembly:
    """Out-of-order per-video feature assembly for the corpus packer.

    With ``--pack_corpus`` a video's clips ride in device batches shared with
    other videos, so its per-clip feature rows arrive in whatever order those
    batches dispatch — and videos complete out of submission order (a short
    video co-packed behind a long one finishes first). This buffer collects
    rows by clip index and rebuilds the in-order feature array once the clip
    stream has finished and every reserved row has landed; only then does the
    run loop hand the assembled output to the (order-preserving) writer.
    Single-threaded: owned and touched only by the packed run loop's thread.
    """

    __slots__ = ("video", "info", "expected", "_reserved", "_rows")

    def __init__(self, video: str, info: dict):
        self.video = video
        self.info = info  # per-video stream metadata (fps, timestamps, …)
        self.expected: Optional[int] = None  # clip count, known at finish()
        self._reserved = 0
        self._rows: Dict[int, np.ndarray] = {}

    def reserve(self) -> int:
        """Claim the next clip index (stream order)."""
        idx = self._reserved
        self._reserved += 1
        return idx

    def put(self, idx: int, row: np.ndarray) -> None:
        self._rows[idx] = row

    def finish(self) -> None:
        """The clip stream ended cleanly; every reserved row is now expected."""
        self.expected = self._reserved

    @property
    def complete(self) -> bool:
        return self.expected is not None and len(self._rows) == self.expected

    def stacked(self, empty_row_shape, dtype=np.float32) -> np.ndarray:
        """The video's features in clip order; a typed empty for zero clips."""
        if not self.expected:
            return np.zeros((0,) + tuple(empty_row_shape), dtype)
        return np.stack([self._rows[i] for i in range(self.expected)])

    def release(self) -> None:
        """Drop the per-clip row buffers once :meth:`stacked` was consumed.

        Each row is a VIEW into the device batch's fetched host array, so a
        lingering assembly pins whole ``(batch_size, …)`` batches — on a
        long-lived serving daemon that is unbounded growth. The run loop
        releases every assembly right after finalize (success or failure);
        :meth:`stacked`'s ``np.stack`` copied the data, so outputs are safe.
        """
        self._rows.clear()


class WriteHandle:
    """Completion token for one video's asynchronous output write."""

    __slots__ = ("video", "_done", "_error")

    def __init__(self, video: str):
        self.video = video
        self._done = threading.Event()
        self._error: Optional[BaseException] = None

    def wait(self, timeout: Optional[float] = None) -> bool:
        """Block until the write completed; re-raises its classified error.

        Returns False if ``timeout`` expired with the write still pending.
        """
        if not self._done.wait(timeout):
            return False
        if self._error is not None:
            raise self._error
        return True

    def done(self) -> bool:
        return self._done.is_set()


class AsyncOutputWriter:
    """Bounded single-writer thread: overlap feature serialization with the
    next video's compute.

    ``action_on_extraction`` + ``mark_done`` previously ran inside the
    per-video loop, serializing multi-GB dense-flow ``.npy`` writes against
    device compute; here they run on one background thread while the loop
    moves on. The PR-1 reliability invariants are preserved by construction
    (pinned by tests/test_async_writer.py + tests/test_fault_injection.py):

    - jobs run strictly in submission order (one queue, one thread);
    - within a job, features are written first (atomic tmp+rename,
      :func:`_atomic_save`) and the done-manifest record appended AFTER —
      a kill at any point leaves either no visible output or a complete one;
    - a failed job surfaces its classified :class:`OutputError` on that
      job's :class:`WriteHandle` only, optionally after transient retries
      (``retry``), never on another video's handle;
    - the queue is bounded: a slow disk backpressures :meth:`submit` (the
      extraction loop) instead of pinning every finished video's features in
      host memory.
    """

    def __init__(self, depth: int = 2, retry: Optional[RetryPolicy] = None):
        self._q: queue.Queue = queue.Queue(maxsize=max(depth, 1))
        self._retry = retry
        self._closed = False
        self._thread = threading.Thread(target=self._drain, daemon=True,
                                        name="output-writer")
        self._thread.start()

    def submit(self, feats_dict: Mapping[str, np.ndarray], video_path: str,
               output_path: str, on_extraction: str = "save_numpy",
               cancelled: Optional[threading.Event] = None) -> WriteHandle:
        """Enqueue one video's output job; blocks when the queue is full.

        ``cancelled``: the attempt's watchdog cancellation event. The job
        re-checks it before touching disk and again between the feature
        writes and the done record — the same two points the inline path
        checks — so an attempt whose timeout fires in the check-to-submit
        window (or mid-write) can never leave a done-manifest record for a
        video the run counted as failed.
        """
        if self._closed:
            raise OutputError("output writer is closed")
        if not self._thread.is_alive():
            raise OutputError("output writer thread died")
        handle = WriteHandle(video_path)
        self._q.put((handle, feats_dict, video_path, output_path, on_extraction,
                     cancelled))
        return handle

    _run_one = staticmethod(write_outputs)  # one write-contract implementation

    def _drain(self) -> None:
        while True:
            item = self._q.get()
            if item is None:
                return
            handle, *job = item
            try:
                if self._retry is not None:
                    # OutputError is transient (disk/NFS pressure clears);
                    # retrying here re-runs idempotent steps only — atomic
                    # saves overwrite, duplicate done records collapse into
                    # the load_done_set set
                    retry_call(lambda: self._run_one(*job), self._retry)  # noqa: B023
                else:
                    self._run_one(*job)
            except Exception as e:  # noqa: BLE001 — fault-barrier: stored on the handle, re-raised classified at the run loop's per-video write reap
                handle._error = e  # thread-shared-state: set before the _done Event; wait() reads after it
            finally:
                handle._done.set()

    def close(self, wait: bool = True) -> None:
        """Stop accepting jobs; by default drain queued jobs first.

        ``wait=True`` joins the thread after it finishes everything already
        queued — on interrupts the physical writes (and their ordering) still
        complete even if the caller no longer collects the handles.
        """
        if self._closed:
            return
        self._closed = True
        self._q.put(None)
        if wait:
            self._thread.join()


def request_result_path(notify_dir: str, request_id: str) -> str:
    """Completion-notification file for one service request
    (:mod:`..serve`): submitters poll for it instead of tailing logs."""
    return os.path.join(notify_dir, f"{request_id}.result.json")


def write_request_result(notify_dir: str, request_id: str,
                         record: Mapping) -> str:
    """Atomically write a request's per-request done/failed manifest.

    One JSON document per request: terminal state, the per-video ``done``
    list and classified ``failed`` records. Written via tmp + ``os.replace``
    like every other output — a submitter that sees the file sees a complete
    record. Returns the path written.
    """
    path = request_result_path(notify_dir, request_id)
    tmp = path + ".tmp"
    try:
        os.makedirs(notify_dir, exist_ok=True)
        with open(tmp, "w") as f:
            json.dump(dict(record), f, indent=2, sort_keys=True)
        os.replace(tmp, path)
    except OSError as e:
        raise OutputError(
            f"failed to write request result {path}: {e}") from e
    finally:
        if os.path.exists(tmp):
            try:
                os.remove(tmp)
            except OSError:
                pass
    return path


def manifest_path(output_path: str) -> str:
    return os.path.join(output_path, MANIFEST_NAME)


def mark_done(output_path: str, video_path: str, keys) -> None:
    """Append a completion record for ``video_path`` to the done-manifest."""
    record = {"video": os.path.abspath(video_path), "keys": sorted(keys)}
    try:
        os.makedirs(output_path, exist_ok=True)
        with open(manifest_path(output_path), "a") as f:
            f.write(json.dumps(record) + "\n")
    except OSError as e:
        raise OutputError(f"cannot append to done-manifest in {output_path}: {e}") from e


def load_done_set(output_path: str) -> set:
    """Absolute video paths already completed according to the manifest.

    Corrupt/undecodable lines (a crash mid-append, manual edits) are counted
    and warned about, not silently skipped: every dropped line is a video that
    ``--resume`` will re-extract, and the operator should know why.
    """
    done = set()
    path = manifest_path(output_path)
    records, corrupt = read_jsonl(path)
    for record in records:
        if "video" in record:
            done.add(record["video"])
        else:
            corrupt += 1
    if corrupt:
        print(
            f"warning: ignored {corrupt} corrupt line(s) in {path}; "
            "the affected videos will be re-extracted on --resume",
            file=sys.stderr,
        )
    return done
