"""PWC-Net optical flow in JAX (NHWC, functional).

Behavioral spec — ``/root/reference/models/pwc/pwc_src/pwc_net.py``:
- Input RGB in [0, 255]; the net flips to BGR and scales /255 (``:229-231``) because
  the pretrained weights are BGR-native.
- Bilinear resize (align_corners=False) to /64-multiple sizes (``:241-245``).
- 6-level feature pyramid, 3 convs per level, LeakyReLU 0.1 (``:44-110``).
- Coarse-to-fine decoders at levels 6→2 (``:112-187``): 81-channel cost volume
  (9×9 displacement window, zero-padded, channel-mean — the CUDA kernel semantics of
  ``correlation.py:44-112``: channel k ↔ (dy=k//9−4, dx=k%9−4)), LeakyReLU'd;
  below level 6 the second feature map is backward-warped by the upsampled flow
  scaled per level (0.625/1.25/2.5/5.0), with the partial-tap zeroing mask
  (``:23-41``); DenseNet-style conv block (new features concatenated in front).
- Dilated refiner on the level-2 feature tail (``:189-210``).
- Output: 20 × bilinear resize of (flow₂ + refinement) to the *original* size, u
  scaled by W/W₆₄, v by H/H₆₄ (``:256-261``).

The cost volume lives in :mod:`video_features_tpu.ops.pallas_corr`: a pure-XLA
formulation (default — 81 shifted products XLA fuses into HBM-friendly passes)
and a hand-tiled Pallas kernel, selected by ``corr_impl``.

Functional over a param pytree (torch checkpoint names, e.g.
``moduleExtractor.moduleOne.0`` — see
:func:`video_features_tpu.weights.convert_torch.convert_pwc`).
"""

from __future__ import annotations

import math
from typing import Dict, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..ops.nnf import conv2d, conv2d_transpose, leaky_relu
from ..ops.pallas_corr import corr81, warp_corr81
from ..ops.warp import resize_bilinear_torch

CORR_RADIUS = 4
CORR_CHANNELS = (2 * CORR_RADIUS + 1) ** 2  # 81

# pyramid level channel counts (level 1..6)
PYR_CHANNELS = (16, 32, 64, 96, 128, 196)
# decoder input channels per level: 81 + fmap + 2 flow + 2 upfeat (level 6: corr only)
DEC_CURRENT = {6: 81, 5: 81 + 128 + 4, 4: 81 + 96 + 4, 3: 81 + 64 + 4, 2: 81 + 32 + 4}
DEC_BACKWARD = {5: 0.625, 4: 1.25, 3: 2.5, 2: 5.0}
DENSE_OUT = (128, 128, 96, 64, 32)  # moduleOne..moduleFiv
LEVEL_NAMES = {2: "moduleTwo", 3: "moduleThr", 4: "moduleFou", 5: "moduleFiv", 6: "moduleSix"}


# re-export: tests and external callers address the cost volume through the model
from ..ops.pallas_corr import corr81_xla as correlation_81  # noqa: E402, F401


def _pyramid(p: Dict, x: jnp.ndarray) -> Tuple[jnp.ndarray, ...]:
    """6-level feature pyramid (pwc_net.py:44-110); 3 convs per level."""
    names = ("moduleOne", "moduleTwo", "moduleThr", "moduleFou", "moduleFiv", "moduleSix")
    feats = []
    for name in names:
        lvl = p[name]
        x = leaky_relu(conv2d(lvl["0"], x, 2, 1))
        x = leaky_relu(conv2d(lvl["2"], x, 1, 1))
        x = leaky_relu(conv2d(lvl["4"], x, 1, 1))
        feats.append(x)
    return tuple(feats)


def _decoder(p: Dict, level: int, f1: jnp.ndarray, f2: jnp.ndarray, prev,
             corr_impl: str = "xla", warp_impl: str = "auto"):
    """One coarse-to-fine stage (pwc_net.py:152-187)."""
    if prev is None:
        volume = leaky_relu(corr81(f1, f2, corr_impl))
        feat = volume
    else:
        flow = conv2d_transpose(p["moduleUpflow"], prev["flow"])
        upfeat = conv2d_transpose(p["moduleUpfeat"], prev["feat"])
        # fused warp+correlate (ops/pallas_corr.warp_corr81): under pallas/auto
        # the warped f2 never exists in HBM — warp gathers were the PWC floor
        volume = leaky_relu(warp_corr81(f1, f2, flow * DEC_BACKWARD[level],
                                        corr_impl, warp_impl))
        feat = jnp.concatenate([volume, f1, flow, upfeat], axis=-1)

    for name in ("moduleOne", "moduleTwo", "moduleThr", "moduleFou", "moduleFiv"):
        feat = jnp.concatenate([leaky_relu(conv2d(p[name]["0"], feat, 1, 1)), feat], axis=-1)
    flow = conv2d(p["moduleSix"]["0"], feat, 1, 1)
    return {"flow": flow, "feat": feat}


def _refiner(p: Dict, feat: jnp.ndarray) -> jnp.ndarray:
    """Dilated context network (pwc_net.py:189-210)."""
    dilations = (1, 2, 4, 8, 16, 1)
    x = feat
    for idx, d in zip(("0", "2", "4", "6", "8", "10"), dilations):
        x = leaky_relu(conv2d(p[idx], x, 1, d, dilation=d))
    return conv2d(p["12"], x, 1, 1)


def _preprocess(image: jnp.ndarray, h64: int, w64: int) -> jnp.ndarray:
    """RGB [0,255] → BGR /255 (pwc_net.py:230) resized to the /64 grid."""
    x = image[..., ::-1].astype(jnp.float32) / 255.0
    if (h64, w64) != image.shape[-3:-1]:
        x = resize_bilinear_torch(x, h64, w64)
    return x


def _decode(params: Dict, pyr1, pyr2, h: int, w: int, h64: int, w64: int,
            corr_impl: str, warp_impl: str = "auto") -> jnp.ndarray:
    """Coarse-to-fine decoders + refiner + output scaling (pwc_net.py:241-261)."""
    est = None
    for level in (6, 5, 4, 3, 2):
        est = _decoder(params[LEVEL_NAMES[level]], level,
                       pyr1[level - 1], pyr2[level - 1], est, corr_impl,
                       warp_impl)

    flow = est["flow"] + _refiner(params["moduleRefiner"]["moduleMain"], est["feat"])
    flow = 20.0 * resize_bilinear_torch(flow.astype(jnp.float32), h, w)
    scale = jnp.asarray([w / w64, h / h64], jnp.float32)
    return flow * scale


def _grid64(h: int, w: int) -> Tuple[int, int]:
    return (int(math.floor(math.ceil(h / 64.0) * 64.0)),
            int(math.floor(math.ceil(w / 64.0) * 64.0)))


def pwc_forward(params: Dict, image1: jnp.ndarray, image2: jnp.ndarray,
                corr_impl: str = "xla", dtype=jnp.float32,
                warp_impl: str = "auto") -> jnp.ndarray:
    """Flow frame1→frame2. Inputs (B, H, W, 3) RGB [0, 255] — uint8 (the
    extractors' wire format; ``_preprocess``'s fp32 cast is the first traced
    op, exact) or float — any size. Returns (B, H, W, 2) float32 flow in
    input-resolution pixels.

    ``corr_impl``: cost-volume implementation (``xla`` | ``pallas``), see
    :mod:`video_features_tpu.ops.pallas_corr`. ``dtype``: conv compute dtype —
    ``jnp.bfloat16`` halves HBM traffic and doubles MXU rate; precision-
    sensitive spots (cost-volume accumulation, warp coordinates, final resize/
    scaling) stay fp32 regardless. Measured drift vs fp32 is recorded in
    ``tests/test_flow_bf16.py`` and docs/architecture.md."""
    b, h, w, _ = image1.shape
    h64, w64 = _grid64(h, w)
    x1 = _preprocess(image1, h64, w64).astype(dtype)
    x2 = _preprocess(image2, h64, w64).astype(dtype)
    pyr1 = _pyramid(params["moduleExtractor"], x1)
    pyr2 = _pyramid(params["moduleExtractor"], x2)
    return _decode(params, pyr1, pyr2, h, w, h64, w64, corr_impl, warp_impl)


def pwc_forward_frames(params: Dict, frames: jnp.ndarray,
                       corr_impl: str = "xla", dtype=jnp.float32,
                       pair_chunk: int = None,
                       warp_impl: str = "auto") -> jnp.ndarray:
    """Flow for all consecutive frame pairs, sharing per-frame features.

    ``frames``: (F, H, W, 3) → (F−1, H, W, 2), or a clip batch (N, F, H, W, 3)
    → (N, F−1, H, W, 2) — pairs never cross clip boundaries.

    TPU-first formulation of the reference's pair loop: the feature pyramid —
    PWC's dominant stage (small-channel convs at 128²/64², tools/profile_pwc.py)
    — is computed ONCE per frame (clips flattened into the conv batch axis) and
    pairs are formed by slicing the shared per-frame features, instead of
    re-encoding ``frames[:-1]`` and ``frames[1:]`` separately (which encodes
    every interior frame twice). Numerics are identical to :func:`pwc_forward`
    on the split pair batches — per-sample conv arithmetic does not depend on
    its batch neighbors.
    """
    lead = frames.shape[:-3]  # (F,) or (N, F)
    n = int(np.prod(lead[:-1], dtype=np.int64)) if len(lead) > 1 else 1
    f = lead[-1]
    h, w = frames.shape[-3:-1]
    h64, w64 = _grid64(h, w)
    flat = _preprocess(frames.reshape((n * f, h, w, 3)), h64, w64).astype(dtype)
    pyr = _pyramid(params["moduleExtractor"], flat)

    def pairs(p, keep_first: bool):
        nf, ph, pw, c = p.shape
        p = p.reshape(n, f, ph, pw, c)
        p = p[:, :-1] if keep_first else p[:, 1:]
        return p.reshape(n * (f - 1), ph, pw, c)

    pyr1 = tuple(pairs(p, True) for p in pyr)
    pyr2 = tuple(pairs(p, False) for p in pyr)
    total = n * (f - 1)
    chunk = min(pair_chunk, total) if pair_chunk else 0
    if chunk > 0 and chunk < total:
        # bound peak decoder memory: the DenseNet decoder activations scale
        # with the pair batch (a 64-pair 65-frame I3D stack at 256×341 blows
        # HBM in one piece — BASELINE.md round-3 note); the shared per-frame
        # pyramid above is computed ONCE either way, only the coarse-to-fine
        # decode runs chunk-by-chunk under lax.map (sequential on device).
        # Non-divisible totals zero-pad the pair axis up to a chunk multiple
        # (padded rows decode to garbage and are sliced off) — the protection
        # must never silently disengage on an odd pair count.
        def chunked(level_maps):
            p1, p2 = level_maps
            return _decode(params, p1, p2, h, w, h64, w64, corr_impl,
                           warp_impl)

        nch = -(-total // chunk)
        pad = nch * chunk - total

        def to_chunks(p):
            if pad:
                p = jnp.concatenate(
                    [p, jnp.zeros((pad,) + p.shape[1:], p.dtype)], axis=0)
            return p.reshape((nch, chunk) + p.shape[1:])

        flow = jax.lax.map(chunked, (tuple(to_chunks(p) for p in pyr1),
                                     tuple(to_chunks(p) for p in pyr2)))
        flow = flow.reshape((nch * chunk, h, w, 2))[:total]
    else:
        flow = _decode(params, pyr1, pyr2, h, w, h64, w64, corr_impl,
                       warp_impl)
    return flow.reshape(lead[:-1] + (f - 1, h, w, 2))


def pwc_forward_frames_sharded(params: Dict, frames: jnp.ndarray,
                               frame_last: jnp.ndarray, mesh,
                               corr_impl: str = "xla", dtype=jnp.float32,
                               warp_impl: str = "auto") -> jnp.ndarray:
    """Encode-once flow over a multi-device mesh, frame axis sharded.

    ``frames``: the window's B source frames (B, H, W, 3) sharded on axis 0
    (B divisible by the mesh size); ``frame_last``: the window's final frame
    (1, H, W, 3), replicated. Returns (B, H, W, 2) flow for the pairs
    ``frames[i] → frames[i+1]`` with ``frames[B] := frame_last``, sharded on
    the pair axis.

    Multi-chip counterpart of :func:`pwc_forward_frames`: the feature
    pyramid — PWC's dominant stage — runs exactly once per source frame on
    the shard that owns it; each shard's one cross-shard pair is formed by
    halo-exchanging the neighbor's first feature map AT EVERY PYRAMID LEVEL
    (:func:`video_features_tpu.ops.halo.boundary_from_next`, six small ICI
    messages per shard per step), and only the replicated ``frame_last`` is
    encoded per-device. Numerics match the pair-split forward up to conv
    reduction order.
    """
    from jax.sharding import PartitionSpec as P

    from ..ops.halo import boundary_from_next, frame_axis_mesh

    b, h, w, _ = frames.shape
    shard_map, axis, n_dev = frame_axis_mesh(mesh, b)
    h64, w64 = _grid64(h, w)

    def local(p, fr, fl):  # per-shard: (k, H, W, 3) main + (1, H, W, 3) last
        x = _preprocess(fr, h64, w64).astype(dtype)
        xl = _preprocess(fl, h64, w64).astype(dtype)
        pyr = _pyramid(p["moduleExtractor"], x)      # 6 levels of (k, hl, wl, c)
        pyr_l = _pyramid(p["moduleExtractor"], xl)   # 6 levels of (1, hl, wl, c)
        pyr2 = tuple(
            jnp.concatenate(
                [lvl[1:], boundary_from_next(lvl[:1], lvl_l, axis, n_dev)],
                axis=0)
            for lvl, lvl_l in zip(pyr, pyr_l))
        return _decode(p, pyr, pyr2, h, w, h64, w64, corr_impl, warp_impl)

    fn = shard_map(local, mesh=mesh,
                   in_specs=(P(), P(axis), P()), out_specs=P(axis))
    return fn(params, frames, frame_last)


# ---------------------------------------------------------------------------
# Shapes / random init. conv: (cin, cout, kh, kw); 'T' prefix marks transpose convs
# whose torch weights are laid out (in, out, kh, kw).
# ---------------------------------------------------------------------------

def pwc_conv_shapes() -> Dict[str, Tuple]:
    shapes: Dict[str, Tuple] = {}
    cin = 3
    for name, cout in zip(
        ("moduleOne", "moduleTwo", "moduleThr", "moduleFou", "moduleFiv", "moduleSix"),
        PYR_CHANNELS,
    ):
        shapes[f"moduleExtractor.{name}.0"] = (cin, cout, 3, 3)
        shapes[f"moduleExtractor.{name}.2"] = (cout, cout, 3, 3)
        shapes[f"moduleExtractor.{name}.4"] = (cout, cout, 3, 3)
        cin = cout

    for level in (6, 5, 4, 3, 2):
        mod = LEVEL_NAMES[level]
        current = DEC_CURRENT[level]
        if level < 6:
            prev_feat = DEC_CURRENT[level + 1] + sum(DENSE_OUT)
            shapes[f"{mod}.moduleUpflow"] = ("T", 2, 2, 4, 4)
            shapes[f"{mod}.moduleUpfeat"] = ("T", prev_feat, 2, 4, 4)
        ch = current
        for name, cout in zip(("moduleOne", "moduleTwo", "moduleThr", "moduleFou", "moduleFiv"),
                              DENSE_OUT):
            shapes[f"{mod}.{name}.0"] = (ch, cout, 3, 3)
            ch += cout
        shapes[f"{mod}.moduleSix.0"] = (ch, 2, 3, 3)

    ch = DEC_CURRENT[2] + sum(DENSE_OUT)
    for idx, (cout, _d) in zip(("0", "2", "4", "6", "8", "10", "12"),
                               ((128, 1), (128, 2), (128, 4), (96, 8), (64, 16), (32, 1), (2, 1))):
        shapes[f"moduleRefiner.moduleMain.{idx}"] = (ch, cout, 3, 3)
        ch = cout
    return shapes


def pwc_init_params(seed: int = 0) -> Dict:
    """Deterministic random param pytree with checkpoint-identical structure."""
    rng = np.random.default_rng(seed)
    tree: Dict = {}
    for name, shape in pwc_conv_shapes().items():
        if shape[0] == "T":
            _, cin, cout, kh, kw = shape
        else:
            cin, cout, kh, kw = shape
        node = tree
        parts = name.split(".")
        for part in parts[:-1]:
            node = node.setdefault(part, {})
        node[parts[-1]] = {
            "kernel": (rng.standard_normal((kh, kw, cin, cout)) * 0.05).astype(np.float32),
            "bias": (rng.standard_normal(cout) * 0.05).astype(np.float32),
        }
    return tree
