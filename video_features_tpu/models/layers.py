"""Shared layers reproducing torch/TF numerics on channel-last layouts.

These are the few primitives whose *exact* semantics decide feature parity with the
reference: inference-mode BatchNorm, the reference's size-independent "TF-SAME"
padding rule, and zero-padded ceil-mode max pooling
(``/root/reference/models/i3d/i3d_src/i3d_net.py:8-34,108-120``).
"""

from __future__ import annotations

import math
from typing import Any, Sequence, Tuple

import flax.linen as nn
import jax.numpy as jnp
from jax import lax

BN_EPS = 1e-5  # torch BatchNorm default


class TorchBatchNorm(nn.Module):
    """Inference BatchNorm: y = (x - mean) / sqrt(var + eps) * scale + bias.

    Running statistics live in ``params`` (converted weights, never updated), so the
    whole model stays one frozen pytree. Affine math runs in fp32 then casts,
    matching torch eval-mode numerics for bf16 compute.
    """

    eps: float = BN_EPS
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x: jnp.ndarray) -> jnp.ndarray:
        c = x.shape[-1]
        scale = self.param("scale", nn.initializers.ones, (c,), jnp.float32)
        bias = self.param("bias", nn.initializers.zeros, (c,), jnp.float32)
        mean = self.param("mean", nn.initializers.zeros, (c,), jnp.float32)
        var = self.param("var", nn.initializers.ones, (c,), jnp.float32)
        inv = jnp.asarray(scale, jnp.float32) / jnp.sqrt(jnp.asarray(var, jnp.float32) + self.eps)
        y = (x.astype(jnp.float32) - mean) * inv + bias
        return y.astype(self.dtype)


def tf_same_pads(kernel: Sequence[int], stride: Sequence[int]) -> Tuple[Tuple[int, int], ...]:
    """Per-axis (lo, hi) pads of the reference's TF-SAME rule: ``max(k - s, 0)``
    split floor/ceil (``i3d_net.py:8-25``). Size-independent — equals true TF SAME
    whenever the input is divisible by the stride, which holds for every I3D layer
    at the 224/64 input geometry."""
    pads = []
    for k, s in zip(kernel, stride):
        p = max(k - s, 0)
        pads.append((p // 2, p - p // 2))
    return tuple(pads)


class S2DStemConv(nn.Module):
    """Stride-2³ 7³ stem conv computed space-to-depth: the MXU formulation.

    Measured result (tools/profile_i3d.py, v5e, 4×64×224² fp32): SLOWER than
    the direct conv — 37 ms vs 10.5 ms — because the fold's input relayout
    costs more than the stem conv, which XLA already runs at ~20 TF/s despite
    cin=3. Kept as a tested opt-in (``VFT_I3D_S2D=1`` /
    ``I3D(s2d_stem=True)``) for hardware/compiler versions where the tradeoff
    flips; the mechanics:

    - pad input with the reference's TF-SAME pads (2, 3) per axis
      (``i3d_net.py:8-25`` rule), plus trailing zeros to an even size;
    - pad the 7-tap kernel to 8 with one trailing zero tap per axis;
    - fold input and kernel by tap parity (k = 2m + r) and run the 4³ conv
      VALID at stride 1.

    Output values equal the direct conv up to fp reassociation (the extra taps
    multiply zeros). The param tree is identical to ``nn.Conv(name="conv3d")``
    — ``kernel`` HWIO — so converted checkpoints load unchanged.
    """

    features: int
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x: jnp.ndarray) -> jnp.ndarray:
        c = x.shape[-1]
        kernel = self.param(
            "kernel",
            nn.initializers.lecun_normal(),
            (7, 7, 7, c, self.features),
            jnp.float32,
        )
        sizes = x.shape[1:-1]
        out_sizes = [(n + 5 - 7) // 2 + 1 for n in sizes]
        pads = []
        for n in sizes:
            lo, hi = 2, 3  # max(k - s, 0) = 5 split floor/ceil
            hi += 1  # kernel tap 8 reads one past the SAME window
            if (n + lo + hi) % 2:
                hi += 1  # even length for the 2-fold
            pads.append((lo, hi))
        xp = jnp.pad(x.astype(self.dtype), [(0, 0)] + pads + [(0, 0)])
        b, tp, hp, wp, _ = xp.shape
        xf = xp.reshape(b, tp // 2, 2, hp // 2, 2, wp // 2, 2, c)
        xf = xf.transpose(0, 1, 3, 5, 2, 4, 6, 7).reshape(
            b, tp // 2, hp // 2, wp // 2, 8 * c
        )
        w8 = jnp.pad(kernel.astype(self.dtype),
                     ((0, 1), (0, 1), (0, 1), (0, 0), (0, 0)))
        wf = w8.reshape(4, 2, 4, 2, 4, 2, c, self.features)
        wf = wf.transpose(0, 2, 4, 1, 3, 5, 6, 7).reshape(4, 4, 4, 8 * c, self.features)
        y = lax.conv_general_dilated(
            xf, wf, window_strides=(1, 1, 1), padding="VALID",
            dimension_numbers=("NDHWC", "DHWIO", "NDHWC"),
        )
        return y[:, : out_sizes[0], : out_sizes[1], : out_sizes[2], :]


class TapConv3D(nn.Module):
    """conv3d lowered as a sum of per-temporal-tap conv2ds (TF-SAME pads by
    default; torch-style explicit per-axis pads via ``padding``).

    Why: on the v5e backend, XLA's conv3d lowering is PATHOLOGICAL in bf16 —
    measured on the I3D stem (4 clips × 64 × 224², 7³/2³): conv3d fp32
    13.5 ms, conv3d bf16 **21.7 ms** (slower than fp32!), while the same math
    as 7 temporal taps of stride-2 conv2d runs **5.5 ms** in bf16 (2.4× the
    fp32 conv3d). This is the root cause of round 2's "bf16 buys I3D nothing":
    the stem is two-thirds of the step and its bf16 conv3d regression swallowed
    every other layer's gain. fp32 keeps the direct conv3d (taps reassociate
    the temporal accumulation — ~1e-6 drift — and fp32 is the bit-parity path).

    Semantics: identical to ``nn.Conv(kernel, stride, pads)`` with ``pads`` =
    the reference's TF-SAME amounts (default) or the explicit per-axis (lo, hi)
    pads given via ``padding`` — the input is zero-padded on every axis, each
    temporal kernel tap becomes a strided conv2d over the (N·T_out) frame
    batch, and the taps are summed. Param tree matches ``nn.Conv`` (``kernel``
    HWIO) so converted checkpoints load unchanged.
    """

    features: int
    kernel: Sequence[int]
    stride: Sequence[int]
    dtype: Any = jnp.float32
    # explicit per-axis (lo, hi) pads (torch-style models, e.g. R(2+1)D);
    # None = the I3D TF-SAME rule
    padding: Any = None

    @nn.compact
    def __call__(self, x: jnp.ndarray) -> jnp.ndarray:
        c = x.shape[-1]
        kt, kh, kw = self.kernel
        st, sh, sw = self.stride
        kernel = self.param(
            "kernel", nn.initializers.lecun_normal(),
            (kt, kh, kw, c, self.features), jnp.float32,
        ).astype(self.dtype)
        x = x.astype(self.dtype)
        pads = (tuple(self.padding) if self.padding is not None
                else tf_same_pads(self.kernel, self.stride))
        (pt0, pt1), sp_h, sp_w = pads
        if pt0 or pt1:
            x = jnp.pad(x, ((0, 0), (pt0, pt1), (0, 0), (0, 0), (0, 0)))
        n, tp, h, w, _ = x.shape
        t_out = (tp - kt) // st + 1
        acc = None
        for dt in range(kt):
            xt = x[:, dt : dt + (t_out - 1) * st + 1 : st]
            xt = xt.reshape((n * t_out, h, w, c))
            y = lax.conv_general_dilated(
                xt, kernel[dt], window_strides=(sh, sw), padding=(sp_h, sp_w),
                dimension_numbers=("NHWC", "HWIO", "NHWC"),
            )
            acc = y if acc is None else acc + y
        return acc.reshape((n, t_out) + acc.shape[1:])


def conv3d_module(features: int, kernel: Sequence[int], stride: Sequence[int],
                  padding: Sequence[Tuple[int, int]], dtype: Any, name: str):
    """The one conv3d chooser (bias-free convs): bf16 routes through
    :class:`TapConv3D` (XLA's conv3d lowering is pathological in bf16 on this
    backend — see TapConv3D's measurements), fp32 keeps ``nn.Conv`` for bit
    parity. ``VFT_I3D_TAP_FP32=1`` opts the fp32 path into the tap lowering
    too, but only for kernels with JOINT spatio-temporal extent (kt>1 and
    kh>1 — the pathological class; R(2+1)D's factored (k,1,1)/(1,k,k) convs
    measured slower under taps and stay direct) — the taps reassociate the
    temporal sum (~1e-6 drift), hence opt-in, not default. ``padding`` is
    REQUIRED explicit per-axis (lo, hi) pads — Flax's string "SAME" pads
    asymmetrically ((2,3) for 7/2) where torch models pad symmetrically, a
    silent numerics trap no call site should be able to hit.
    """
    import os

    padding = tuple(tuple(p) for p in padding)
    joint_extent = kernel[0] > 1 and (kernel[1] > 1 or kernel[2] > 1)
    tap_fp32 = os.environ.get("VFT_I3D_TAP_FP32") == "1" and joint_extent
    if dtype == jnp.bfloat16 or tap_fp32:
        return TapConv3D(features, tuple(kernel), tuple(stride), dtype=dtype,
                         padding=padding, name=name)
    return nn.Conv(features, tuple(kernel), strides=tuple(stride),
                   padding=padding, use_bias=False, dtype=dtype, name=name)


def max_pool_tf_same(
    x: jnp.ndarray, kernel: Sequence[int], stride: Sequence[int]
) -> jnp.ndarray:
    """Zero-padded TF-SAME max pool with torch ceil_mode semantics on NDHWC/NHWC.

    The reference zero-pads (not -inf: activations are post-ReLU, so zero is a
    neutral element) then pools with ``ceil_mode=True`` (``i3d_net.py:108-120``).
    Ceil-mode windows that run past the padded input ignore the overhang — expressed
    here as extra -inf padding on the high side of each axis.
    """
    spatial = x.shape[1:-1]
    zero_pads = tf_same_pads(kernel, stride)
    cfg_pad = [(0, 0)]
    cfg_win = [1]
    cfg_str = [1]
    for size, k, s, (lo, hi) in zip(spatial, kernel, stride, zero_pads):
        padded = size + lo + hi
        n_out = max(math.ceil((padded - k) / s), 0) + 1
        extra = (n_out - 1) * s + k - padded
        cfg_pad.append((0, max(extra, 0)))
        cfg_win.append(k)
        cfg_str.append(s)
    cfg_pad.append((0, 0))
    cfg_win.append(1)
    cfg_str.append(1)

    x = jnp.pad(
        x,
        [(0, 0)] + [(lo, hi) for lo, hi in zero_pads] + [(0, 0)],
        constant_values=0,
    )
    return lax.reduce_window(
        x,
        -jnp.inf if jnp.issubdtype(x.dtype, jnp.floating) else jnp.iinfo(x.dtype).min,
        lax.max,
        tuple(cfg_win),
        tuple(cfg_str),
        cfg_pad,
    )


def avg_pool_valid(x: jnp.ndarray, kernel: Sequence[int], stride: Sequence[int]) -> jnp.ndarray:
    """VALID average pool on channel-last input (torch ``AvgPool3d`` semantics)."""
    window = (1, *kernel, 1)
    strides = (1, *stride, 1)
    summed = lax.reduce_window(x, 0.0, lax.add, window, strides, "VALID")
    return summed / math.prod(kernel)
