"""RAFT optical flow in JAX (NHWC, functional, scan-tied update loop).

Behavioral spec — ``/root/reference/models/raft/raft_src/``:
- Input pair normalized ``2·(x/255) − 1`` (``raft.py:118-119``); images pre-padded to
  /8 multiples by the extractor (replicate, sintel split — ``raft.py:27-44``).
- ``fnet`` (instance norm) → 256-d features at 1/8 res for both frames;
  ``cnet`` (eval batch norm) → 128 tanh hidden + 128 relu context (``raft.py:127-143``).
- All-pairs correlation ``⟨f1, f2⟩/√256`` pooled into a 4-level pyramid
  (``corr.py:12-27,52-60``); each iteration gathers a 9×9 bilinear window per level
  at the current flow (``corr.py:29-50``) — torch's channel order (the reference
  swaps dx/dy when building the delta grid, ``corr.py:37-43``) is reproduced exactly
  because the update-block weights were trained against it.
- 20 iterations of motion encoder + separable ConvGRU + flow head
  (``update.py:37-139``, ``raft.py:151-168``) — here one ``lax.scan`` body.
- Convex upsampling ×8 with a learned 9-tap softmax mask (``raft.py:100-111``),
  computed ONCE after the loop (the reference recomputes it every iteration and
  discards all but the last in test mode — identical output, 20× less upsample work).

Weight-tied loops are why this model is functional over a param pytree instead of a
linen module: ``lax.scan`` over pure functions keeps the compiled HLO one body long.
Param tree names mirror the torch checkpoint (minus the ``module.`` prefix) so
conversion is mechanical (:func:`video_features_tpu.weights.convert_torch.convert_raft`).
"""

from __future__ import annotations

import math
from typing import Dict, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from ..ops.nnf import avg_pool2d, batch_norm_eval, conv2d, instance_norm
from ..ops.warp import coords_grid, equalize_chunks

HIDDEN_DIM = 128
CONTEXT_DIM = 128
CORR_LEVELS = 4
CORR_RADIUS = 4
ITERS = 20  # reference inference default (raft.py:115)

# HBM budget for the materialized all-pairs pyramid; past it, corr_impl
# "auto" switches to the on-demand path (the alt_cuda_corr equivalent).
# ~4 GiB leaves room for the one-hot selectors, activations, and double
# buffering on a 16 GiB chip; override via VFT_RAFT_VOLUME_BUDGET (bytes).
_VOLUME_HBM_BUDGET = 4 * 1024**3


def resolve_corr_impl(corr_impl: str, n_pairs: int, h: int, w: int,
                      dtype=jnp.float32, n_devices: int = 1) -> str:
    """Resolve ``auto`` per frame geometry: the reference-default materialized
    volume while it fits, the O(H·W·D) on-demand GATHER path beyond
    (``VFT_RAFT_ON_DEMAND_IMPL=matmul`` opts into the MXU volume remat once a
    1080p TPU sweep justifies it — its FLOPs scale with frame area, the
    gather's with the fixed window; see the big-frame comment below). In fp32
    the paths agree to reduction-order ulps (~3e-3 px
    through 20 iterations, tools/profile_on_demand.py); under
    ``dtype=bfloat16`` the volume path stores a bf16 pyramid while the remat
    rounds the einsum inputs — the same one-bf16-rounding drift class,
    bounded in tests/test_flow_bf16.py.

    The pyramid holds ``n_pairs · (h/8·w/8)² · Σ4⁻ˡ`` correlation values
    (corr.py:12-27 geometry); e.g. 16 pairs at 1080p → ~89 GB fp32, several
    times HBM — exactly the case the reference's alt_cuda_corr serves.

    ``n_devices``: mesh size of the surrounding sharded step. Inside a jit the
    traced ``n_pairs`` is the GLOBAL pair count but each device materializes
    only its ``n_pairs / n_devices`` shard of the pyramid, so the budget
    (``VFT_RAFT_VOLUME_BUDGET`` bytes, per device) is compared against the
    per-device share — without it a mesh-sharded step near the boundary would
    needlessly take the ~40× slower on-demand path.
    """
    if corr_impl != "auto":
        return corr_impl
    import os

    budget = float(os.environ.get("VFT_RAFT_VOLUME_BUDGET", _VOLUME_HBM_BUDGET))
    q = (h // 8) * (w // 8)
    itemsize = 2 if dtype == jnp.bfloat16 else 4
    per_device_pairs = max(1, -(-n_pairs // max(n_devices, 1)))
    vol_bytes = per_device_pairs * q * q * itemsize * (1 + 1 / 4 + 1 / 16 + 1 / 64)
    if vol_bytes <= budget:
        return "volume"
    # past the budget, the GATHER formulation is the default (ADVICE r5
    # revert): the matmul remat's contraction FLOPs per query scale with the
    # level's hi·wi (quadratic in frame area) while the gather's scale with
    # the fixed 10×10 window, so the 3.2-3.6× win measured at 64×64 on CPU
    # can invert by ~300× more remat work at 1080p — exactly the regime auto
    # selects this path. Flip back to matmul only on a committed 1080p TPU
    # measurement from tools/profile_on_demand.py
    # (VFT_RAFT_ON_DEMAND_IMPL=matmul opts in per run meanwhile).
    choice = os.environ.get("VFT_RAFT_ON_DEMAND_IMPL", "gather")
    if choice not in ("gather", "matmul"):
        # fail loudly like VFT_RAFT_VOLUME_BUDGET does — a typo'd revert
        # that silently stayed on matmul would mislabel a measurement
        raise ValueError(
            f"VFT_RAFT_ON_DEMAND_IMPL must be gather|matmul, got {choice!r}")
    return "on_demand" if choice == "gather" else "on_demand_matmul"

# (name, cin, cout, kernel, stride, pad) for plain convs; residual layers described
# structurally in _encoder below.
ENCODER_DIMS = (64, 64, 96, 128)  # stem, layer1, layer2, layer3


def _relu(x):
    return jnp.maximum(x, 0)


def _norm(p: dict, x: jnp.ndarray, kind: str, name: str) -> jnp.ndarray:
    if kind == "instance":
        return instance_norm(x)
    if kind == "batch":
        return batch_norm_eval(p[name], x)
    return x


def _residual_block(p: dict, x: jnp.ndarray, kind: str, stride: int) -> jnp.ndarray:
    y = _relu(_norm(p, conv2d(p["conv1"], x, stride, 1), kind, "norm1"))
    y = _relu(_norm(p, conv2d(p["conv2"], y, 1, 1), kind, "norm2"))
    if stride != 1:
        x = _norm(p, conv2d(p["downsample.0"], x, stride, 0), kind, "norm3")
    return _relu(x + y)


def _encoder(p: dict, x: jnp.ndarray, kind: str) -> jnp.ndarray:
    """BasicEncoder (extractor.py:118-192): 7×7/2 stem + 3 residual stages + 1×1."""
    x = _relu(_norm(p, conv2d(p["conv1"], x, 2, 3), kind, "norm1"))
    for stage, stride in (("layer1", 1), ("layer2", 2), ("layer3", 2)):
        x = _residual_block(p[f"{stage}.0"], x, kind, stride)
        x = _residual_block(p[f"{stage}.1"], x, kind, 1)
    return conv2d(p["conv2"], x, 1, 0)


def _build_pyramid(f1: jnp.ndarray, f2: jnp.ndarray,
                   dtype=jnp.float32) -> Tuple[jnp.ndarray, ...]:
    """All-pairs correlation volume pooled over target resolution (corr.py:12-27).

    ``dtype=bfloat16`` stores the (H·W)² volume in bf16 — half the HBM for the
    framework's largest tensor and half the lookup read traffic; the einsum
    still accumulates in fp32 before the cast.
    """
    b, h, w, d = f1.shape
    corr = jnp.einsum("bijc,bklc->bijkl", f1.astype(jnp.float32), f2.astype(jnp.float32))
    corr = (corr / math.sqrt(d)).astype(dtype)
    corr = corr.reshape(b * h * w, h, w, 1)
    pyramid = [corr]
    for _ in range(CORR_LEVELS - 1):
        corr = avg_pool2d(corr, 2, 2)  # fp32 accumulation, cast back inside
        pyramid.append(corr)
    return tuple(pyramid)


def _int_window(c: jnp.ndarray):
    """Integer tap indices and bilinear fractions for a 10×10 window.

    ``c``: (..., 2) level-scaled window centers. Returns ``(ix, iy, fx, fy)``
    with taps (..., 10) covering offsets −4…+5 (all 81 corners of the 9×9
    window share these integer taps) and fractions (...,).
    """
    cf = jnp.floor(c)
    off = jnp.arange(-CORR_RADIUS, CORR_RADIUS + 2, dtype=jnp.int32)  # (10,)
    ix = cf[..., 0].astype(jnp.int32)[..., None] + off
    iy = cf[..., 1].astype(jnp.int32)[..., None] + off
    return ix, iy, c[..., 0] - cf[..., 0], c[..., 1] - cf[..., 1]


def _tap_index_mask(ix: jnp.ndarray, iy: jnp.ndarray, hi: int, wi: int):
    """Clipped per-image flat indices and in-bounds mask for a (10y, 10x) patch.

    ``idx`` (..., 10y, 10x) indexes a row-major (hi·wi) plane; ``mask`` zeroes
    out-of-bounds taps after the clipped gather — the reference's zero-padding
    semantics (grid_sample padding_mode='zeros', per corner tap). Per-image
    offsets stay bounded by hi·wi (a global arange(n)·hi·wi base would overflow
    int32 for large frames × batch).
    """
    idx = (jnp.clip(iy, 0, hi - 1)[..., :, None] * wi
           + jnp.clip(ix, 0, wi - 1)[..., None, :])
    mask = (((iy >= 0) & (iy <= hi - 1))[..., :, None]
            & ((ix >= 0) & (ix <= wi - 1))[..., None, :])
    return idx, mask


def _combine_window(patch: jnp.ndarray, fx: jnp.ndarray, fy: jnp.ndarray) -> jnp.ndarray:
    """(..., 10y, 10x) integer patch → (..., 81) bilinear window values.

    Four shifted elementwise combinations (identical arithmetic to per-point
    bilinear sampling: 4 products + 3 adds per value), flattened x-major —
    channel k = i·9 + j samples (δ_i in x, δ_j in y), the reference's
    delta-grid axis swap (corr.py:37-43) that the update-block weights were
    trained against.
    """
    fx = fx.astype(patch.dtype)[..., None, None]  # keep bf16 paths bf16 (a
    fy = fy.astype(patch.dtype)[..., None, None]  # fp32 fraction would promote)
    v = (
        (1 - fy) * (1 - fx) * patch[..., :-1, :-1]
        + (1 - fy) * fx * patch[..., :-1, 1:]
        + fy * (1 - fx) * patch[..., 1:, :-1]
        + fy * fx * patch[..., 1:, 1:]
    )  # (..., 9y, 9x)
    sw = jnp.swapaxes(v, -1, -2)  # x-major
    return sw.reshape(sw.shape[:-2] + ((2 * CORR_RADIUS + 1) ** 2,))


def _lookup(pyramid, coords: jnp.ndarray, impl: str = "matmul") -> jnp.ndarray:
    """9×9 bilinear window per level around the current correspondence,
    flattened i-major (δx-major) into 81 channels per level.

    TPU formulation: every window point shares the query's fractional offset
    (the 81 deltas are integers), so the whole window is ONE 10×10 integer
    patch per query, and the 81 bilinear values are four shifted elementwise
    combinations of that patch — identical arithmetic to per-point bilinear
    sampling (4 products + 3 adds per value).

    The patch extraction itself has two lowerings:
    - ``matmul`` (default): two one-hot batched matmuls — rows then columns —
      so the data-dependent 2-D slice runs on the MXU instead of the scalar
      gather unit. Out-of-bounds taps fall out as all-zero one-hot rows, which
      IS the reference's zero-padding semantics (grid_sample
      padding_mode='zeros', per corner tap). Measured on TPU v5e at batch
      16 × 256² (tools/profile_raft.py): 20 lookups 1370 ms → 63 ms; full
      20-iteration forward 1551 ms → 100 ms (15.5×).
    - ``gather``: one ``take_along_axis`` patch gather per level (the exact
      arithmetic reference path; also the faster lowering on CPU).
    """
    b, h, w, _ = coords.shape
    r = CORR_RADIUS
    n = b * h * w
    win = 2 * r + 2  # 10 taps per axis
    out = []
    for i, corr in enumerate(pyramid):
        hi, wi = corr.shape[1], corr.shape[2]
        if hi == 0 or wi == 0:
            # tiny inputs can pool a pyramid level away entirely; every tap is
            # out of bounds → zeros (the per-corner mask semantics)
            out.append(jnp.zeros((b, h, w, (2 * r + 1) ** 2), corr.dtype))
            continue
        ix, iy, fx, fy = _int_window((coords / 2**i).reshape(n, 2))
        if impl == "matmul":
            # one-hot row/column selectors; comparisons against the level's
            # iota leave out-of-bounds taps as all-zero rows — exactly the
            # zero-padding semantics (grid_sample padding_mode='zeros')
            sy = (iy[:, :, None] == jnp.arange(hi, dtype=jnp.int32)[None, None, :])
            sx = (ix[:, :, None] == jnp.arange(wi, dtype=jnp.int32)[None, None, :])
            # fp32 volume: HIGHEST — selection against 0/1 has one nonzero
            # product per output, so the lowering is bit-identical to the
            # gather path even when surrounding convs run default precision.
            # bf16 volume (flow_dtype bf16): default precision — a one-hot
            # selection has no accumulation error at ANY precision, only the
            # value rounding the bf16 volume already paid, and the MXU runs
            # single-pass instead of the 6-pass fp32 sequence (the lookup is
            # 70% of the fp32 step: 77.7 of 111 ms at b16·256²,
            # tools/profile_raft.py).
            prec = (lax.Precision.HIGHEST if corr.dtype == jnp.float32
                    else lax.Precision.DEFAULT)
            rows = jnp.einsum("npi,nij->npj", sy.astype(corr.dtype),
                              corr.reshape(n, hi, wi), precision=prec)
            patch = jnp.einsum("npj,nqj->npq", rows, sx.astype(corr.dtype),
                               precision=prec)
        elif impl == "gather":
            idx, mask = _tap_index_mask(ix, iy, hi, wi)
            patch = jnp.take_along_axis(corr.reshape(n, hi * wi),
                                        idx.reshape(n, win * win), axis=1)
            patch = patch.reshape(n, win, win)  # ONE gather per level
            patch = patch * mask.astype(patch.dtype)
        else:
            raise ValueError(f"lookup impl must be matmul|gather, got {impl!r}")
        out.append(_combine_window(patch, fx, fy).reshape(b, h, w, -1))
    return jnp.concatenate(out, axis=-1)  # (B, H, W, 4·81)


def _build_f2_pyramid(f2: jnp.ndarray) -> Tuple[jnp.ndarray, ...]:
    """Pooled TARGET features for on-demand correlation.

    The TPU-native equivalent of the reference's optional ``alt_cuda_corr``
    extension (corr.py:63-91): instead of materializing the (H·W)² volume,
    exploit linearity — avg-pooling the volume over target coordinates equals
    correlating against avg-pooled f2, and bilinear lookup is linear too, so
    ``sample(pool(corr))(x, p) == ⟨f1(x), sample(pool(f2))(p)⟩``. Memory drops
    from O((H·W)²) to O(H·W·D); FLOPs drop too once H·W > 81·levels·iters.
    """
    pyr = [f2]
    for _ in range(CORR_LEVELS - 1):
        pyr.append(avg_pool2d(pyr[-1], 2, 2))
    return tuple(pyr)


def _lookup_on_demand(f1: jnp.ndarray, f2_pyramid, coords: jnp.ndarray,
                      impl: str = "gather",
                      chunk_budget: int = 16_000_000,
                      dtype=jnp.float32) -> jnp.ndarray:
    """Correlation window computed on the fly from pooled-f2 features — the
    memory-bounded path (O(H·W·D), no persistent (H·W)² volume).

    ``impl='gather'``: bilinear interpolation commutes with the channel dot
    product, so instead of sampling f2 at 81 fractional points (324 corner
    gathers of D-vectors per query), gather ONE 10×10 integer patch of f2
    vectors per query per level, contract with f1 on the MXU, and form the 81
    bilinear values as four shifted combinations of the (10, 10) correlation
    patch — ~3× fewer gathered bytes and one gather per level. Numerics
    identical to the fractional-point formulation up to fp reduction order
    (the bilinear weights multiply the same products).

    ``impl='matmul'``: zero gathers — rematerialize the chunk's slice of the
    correlation volume each call (``einsum('bnc,bijc->bnij')``, pure MXU) and
    select the 10×10 window with the volume path's one-hot matmuls
    (models/raft.py one-hot trick, 15.5× there). The volume slice does not
    persist: O(chunk·hᵢ·wᵢ) live bytes, bounded by ``chunk_budget`` elements
    per batch element via ``lax.scan`` over query chunks. Against the gather
    impl this trades ITERS× recomputed volume FLOPs (MXU-cheap) for zero
    scalar-unit gather traffic (the measured 40× cliff); against ``volume``
    it trades the same FLOPs for the O((H·W)²) HBM the big-frame regime
    doesn't have. Reference anchor: ``alt_cuda_corr``
    (/root/reference/models/raft/corr.py:63-91) recomputes per-iteration too.

    ``dtype=bfloat16`` (matmul impl only): the vol einsum's INPUTS are cast
    bf16 (fp32 accumulation via preferred_element_type) — halves the remat's
    HBM reads and runs single-pass on the MXU instead of the fp32 3-pass
    default. Same drift class as the volume path's bf16 pyramid storage
    (that path rounds the correlation values AFTER the product; this rounds
    the features BEFORE — both one bf16 rounding of the lookup input,
    bounded in tests/test_flow_bf16.py). The gather impl stays fp32: its
    cost is the gather, not the contraction.
    """
    if impl not in ("gather", "matmul"):
        raise ValueError(
            f"on-demand lookup impl must be gather|matmul, got {impl!r}")
    b, h, w, d = f1.shape
    r = CORR_RADIUS
    win = 2 * r + 2  # 10 taps per axis
    scale = 1.0 / math.sqrt(d)
    f1 = f1.astype(jnp.float32)
    n = h * w
    out = []
    for i, f2i in enumerate(f2_pyramid):
        hi, wi = f2i.shape[1], f2i.shape[2]
        if hi == 0 or wi == 0:
            # tiny inputs can pool a pyramid level away entirely; every tap is
            # out of bounds → zeros (the per-corner mask semantics)
            out.append(jnp.zeros((b, h, w, (2 * r + 1) ** 2), jnp.float32))
            continue
        ix, iy, fx, fy = _int_window((coords / 2**i).reshape(b, n, 2))
        if impl == "matmul":
            n_chunks, chunk, pad = equalize_chunks(n, chunk_budget // (hi * wi))

            def prep(a):  # (b, n, ...) → (n_chunks, b, chunk, ...)
                a = jnp.pad(a, ((0, 0), (0, pad)) + ((0, 0),) * (a.ndim - 2))
                return a.reshape((b, n_chunks, chunk) + a.shape[2:]).swapaxes(0, 1)

            vol_in = jnp.bfloat16 if dtype == jnp.bfloat16 else jnp.float32
            f2f = f2i.astype(vol_in)
            iota_h = jnp.arange(hi, dtype=jnp.int32)
            iota_w = jnp.arange(wi, dtype=jnp.int32)

            def body(_, args):
                f1c, ixc, iyc = args  # (b, chunk, d), (b, chunk, 10), ...
                # fp32 mode: DEFAULT precision — the same contraction
                # precision the gather impl's f1·patch einsum runs at.
                # bf16 mode: bf16 inputs (pre-cast below, so the scanned f1
                # slices are read half-width too), fp32 accumulator
                vol = jnp.einsum("bnc,bijc->bnij", f1c, f2f,
                                 preferred_element_type=jnp.float32)
                sy = (iyc[..., None] == iota_h).astype(jnp.float32)
                sx = (ixc[..., None] == iota_w).astype(jnp.float32)
                # HIGHEST: one-hot selection must pass vol values through
                # unrounded (one nonzero product per output); costs only
                # 10/d of the vol einsum
                rows = jnp.einsum("bnpi,bnij->bnpj", sy, vol,
                                  precision=lax.Precision.HIGHEST)
                patch = jnp.einsum("bnqj,bnpj->bnpq", sx, rows,
                                   precision=lax.Precision.HIGHEST)
                return None, patch * scale

            _, patch = lax.scan(
                body, None,
                (prep(f1.reshape(b, n, d).astype(vol_in)), prep(ix), prep(iy)))
            patch = patch.swapaxes(0, 1).reshape(b, n_chunks * chunk,
                                                 win, win)[:, :n]
            # OOB taps already zero (equality falls off the iota) — same
            # semantics as the gather impl's explicit mask
        else:
            idx, mask = _tap_index_mask(ix, iy, hi, wi)  # (B, HW, 10y, 10x)
            flat = f2i.reshape(b, hi * wi, -1).astype(jnp.float32)
            patch_f = jnp.take_along_axis(
                flat[:, None], idx.reshape(b, 1, n * win * win)[..., None], axis=2
            ).reshape(b, n, win, win, -1)  # (B, HW, 10, 10, D) one gather/level
            patch = jnp.einsum("bnc,bnpqc->bnpq", f1.reshape(b, n, d), patch_f) * scale
            patch = patch * mask
        out.append(_combine_window(patch, fx, fy).reshape(b, h, w, -1))
    return jnp.concatenate(out, axis=-1)


def _motion_encoder(p: dict, flow: jnp.ndarray, corr: jnp.ndarray) -> jnp.ndarray:
    cor = _relu(conv2d(p["convc1"], corr, 1, 0))
    cor = _relu(conv2d(p["convc2"], cor, 1, 1))
    flo = _relu(conv2d(p["convf1"], flow, 1, 3))
    flo = _relu(conv2d(p["convf2"], flo, 1, 1))
    out = _relu(conv2d(p["conv"], jnp.concatenate([cor, flo], -1), 1, 1))
    return jnp.concatenate([out, flow], -1)


def _sep_conv_gru(p: dict, h: jnp.ndarray, x: jnp.ndarray) -> jnp.ndarray:
    """Separable ConvGRU: a 1×5 pass then a 5×1 pass (update.py:37-64).

    MXU shaping: ``convz``/``convr`` consume the same ``hx`` input, so their
    kernels are concatenated along the output-channel axis into ONE conv per
    direction (2 convs per pass instead of 3; the checkpoint keeps the original
    per-gate names — fusion happens here, where the concat is loop-invariant
    and XLA hoists it out of the scan). Bitwise identical to separate convs:
    each output channel's contraction is unchanged.
    """
    for suffix, pad in (("1", (0, 2)), ("2", (2, 0))):
        hx = jnp.concatenate([h, x], -1)
        pz, pr = p[f"convz{suffix}"], p[f"convr{suffix}"]
        zr = conv2d(
            {"kernel": jnp.concatenate([pz["kernel"], pr["kernel"]], -1),
             "bias": jnp.concatenate([pz["bias"], pr["bias"]], -1)},
            hx, 1, pad)
        z = jax.nn.sigmoid(zr[..., :HIDDEN_DIM])
        r = jax.nn.sigmoid(zr[..., HIDDEN_DIM:])
        q = jnp.tanh(conv2d(p[f"convq{suffix}"], jnp.concatenate([r * h, x], -1), 1, pad))
        h = (1 - z) * h + z * q
    return h


def _convex_upsample(flow: jnp.ndarray, mask: jnp.ndarray) -> jnp.ndarray:
    """×8 convex combination of 3×3 neighbors (raft.py:100-111)."""
    from ..ops.nnf import extract_patches_3x3

    b, h, w, _ = flow.shape
    m = mask.astype(jnp.float32).reshape(b, h, w, 9, 8, 8)
    m = jax.nn.softmax(m, axis=3)
    patches = extract_patches_3x3(8.0 * flow)  # (B, H, W, 9, 2)
    up = jnp.einsum("bhwkij,bhwkc->bhwijc", m, patches)
    return up.transpose(0, 1, 3, 2, 4, 5).reshape(b, 8 * h, 8 * w, 2)


def raft_forward(params: Dict, image1: jnp.ndarray, image2: jnp.ndarray,
                 iters: int = ITERS, taps: Dict = None,
                 corr_impl: str = "volume", dtype=jnp.float32,
                 n_devices: int = 1) -> jnp.ndarray:
    """Flow from frame1 to frame2. Inputs (B, H, W, 3) RGB in [0, 255] —
    uint8 (the extractors' wire format: the u8→fp32 cast below is the first
    traced op, exact, so host staging ships quarter the bytes) or float32 —
    H and W divisible by 8. Returns (B, H, W, 2) flow in pixels (u, v).

    ``corr_impl``: ``volume`` materializes the all-pairs pyramid (reference
    default path, corr.py:12-60) with the MXU one-hot-matmul window lookup;
    ``volume_gather`` is the same pyramid with the scalar-gather lookup (same
    bits; faster on CPU, ~15× slower on TPU); ``on_demand`` computes window
    correlations per iteration from pooled f2 features (the ``alt_cuda_corr``
    equivalent — O(H·W·D) memory instead of O((H·W)²) for frames whose volume
    outgrows HBM, see :func:`_build_f2_pyramid`; gather-bound, so it trades
    ~40× speed for that memory ceiling); ``on_demand_matmul`` keeps the
    memory ceiling but remats the volume slice per iteration on the MXU
    instead of gathering (opt-in via ``VFT_RAFT_ON_DEMAND_IMPL=matmul``;
    ``auto``'s big-frame choice is ``on_demand`` pending a 1080p TPU sweep —
    see :func:`resolve_corr_impl` and :func:`_lookup_on_demand`).

    ``taps``: debug-only dict filled with per-stage activations (fnet/cnet/corr/
    per-iteration flow) for the layer-diff parity harness (tools/layer_diff.py);
    tapping unrolls the update loop in Python instead of ``lax.scan``.

    ``dtype``: conv compute dtype. ``jnp.bfloat16`` runs encoders/GRU convs in
    bf16 and STORES the correlation pyramid in bf16 (fp32-accumulated before
    the cast; halves the framework's largest tensor) with the window lookup at
    default MXU precision — exact selection, bf16-rounded values. The
    coordinate carry and convex upsample stay fp32 (20 accumulated deltas are
    the refinement's sensitive spot). Measured drift vs fp32:
    tests/test_flow_bf16.py, docs/architecture.md.
    """
    corr_impl = resolve_corr_impl(corr_impl, image1.shape[0],
                                  image1.shape[1], image1.shape[2], dtype,
                                  n_devices)
    if corr_impl not in ("volume", "volume_gather", "on_demand", "on_demand_matmul"):
        raise ValueError(
            f"corr_impl must be auto|volume|volume_gather|on_demand|"
            f"on_demand_matmul, got {corr_impl!r}")
    x1 = (2.0 * (image1.astype(jnp.float32) / 255.0) - 1.0).astype(dtype)
    x2 = (2.0 * (image2.astype(jnp.float32) / 255.0) - 1.0).astype(dtype)

    f1 = _encoder(params["fnet"], x1, "instance").astype(jnp.float32)
    f2 = _encoder(params["fnet"], x2, "instance").astype(jnp.float32)
    cnet = _encoder(params["cnet"], x1, "batch")
    return _refine_flow(params, f1, f2, cnet, iters, taps, corr_impl, dtype)


def raft_forward_frames(params: Dict, frames: jnp.ndarray, iters: int = ITERS,
                        corr_impl: str = "volume", dtype=jnp.float32,
                        n_devices: int = 1) -> jnp.ndarray:
    """Flow for all consecutive frame pairs, sharing per-frame features.

    ``frames``: (F, H, W, 3) → (F−1, H, W, 2), or a clip batch (N, F, H, W, 3)
    → (N, F−1, H, W, 2) — pairs never cross clip boundaries. uint8 or float
    RGB in [0, 255] (uint8 is the wire format; the fp32 cast is traced).

    TPU-first formulation of the reference's pair loop: ``fnet`` runs ONCE per
    frame (clips flattened into the conv batch axis) and pairs are formed by
    slicing the shared features, instead of encoding ``frames[:-1]`` and
    ``frames[1:]`` separately (every interior frame twice); ``cnet`` runs on
    the F−1 source frames as before. Numerics identical to
    :func:`raft_forward` on split pair batches — per-sample conv arithmetic
    does not depend on batch neighbors.
    """
    lead = frames.shape[:-3]  # (F,) or (N, F)
    n = int(np.prod(lead[:-1], dtype=np.int64)) if len(lead) > 1 else 1
    nf = lead[-1]
    h, w = frames.shape[-3:-1]
    corr_impl = resolve_corr_impl(corr_impl, n * (nf - 1), h, w, dtype,
                                  n_devices)
    if corr_impl not in ("volume", "volume_gather", "on_demand", "on_demand_matmul"):
        raise ValueError(
            f"corr_impl must be auto|volume|volume_gather|on_demand|"
            f"on_demand_matmul, got {corr_impl!r}")
    x = (2.0 * (frames.astype(jnp.float32) / 255.0) - 1.0).astype(dtype)
    x = x.reshape((n * nf, h, w, 3))
    feat = _encoder(params["fnet"], x, "instance").astype(jnp.float32)

    def pairs(p, keep_first: bool):
        _, ph, pw, c = p.shape
        p = p.reshape(n, nf, ph, pw, c)
        p = p[:, :-1] if keep_first else p[:, 1:]
        return p.reshape(n * (nf - 1), ph, pw, c)

    cnet = _encoder(params["cnet"], pairs(x, True), "batch")
    flow = _refine_flow(params, pairs(feat, True), pairs(feat, False), cnet,
                        iters, None, corr_impl, dtype)
    return flow.reshape(lead[:-1] + (nf - 1, h, w, 2))


def raft_forward_frames_sharded(params: Dict, frames: jnp.ndarray,
                                frame_last: jnp.ndarray, mesh,
                                iters: int = ITERS, corr_impl: str = "volume",
                                dtype=jnp.float32) -> jnp.ndarray:
    """Encode-once flow over a multi-device mesh, frame axis sharded.

    ``frames``: the window's B source frames (B, H, W, 3), sharded on axis 0
    (B divisible by the mesh size); ``frame_last``: the window's final frame
    (1, H, W, 3), replicated. Returns (B, H, W, 2) flow for the pairs
    ``frames[i] → frames[i+1]`` with ``frames[B] := frame_last`` — the flow
    of the (B+1)-frame window ``[frames; frame_last]``, sharded on the pair
    axis.

    Multi-chip counterpart of :func:`raft_forward_frames`: the B+1 frames of
    a window cannot shard evenly, so the pair-split step re-encoded every
    interior frame twice on meshes > 1 device. Here ``fnet``/``cnet`` run
    exactly once per source frame on the shard that owns it, each shard's one
    cross-shard pair is formed by halo-exchanging the NEIGHBOR's first fnet
    feature map over ICI (:func:`video_features_tpu.ops.halo.
    boundary_from_next` — one (1, H/8, W/8, 256) message per shard per step),
    and only the single replicated ``frame_last`` is encoded per-device.
    Numerics match the pair-split forward up to conv reduction order.
    """
    from jax.sharding import PartitionSpec as P

    from ..ops.halo import boundary_from_next, frame_axis_mesh

    b, h, w, _ = frames.shape
    shard_map, axis, n_dev = frame_axis_mesh(mesh, b)
    corr_impl = resolve_corr_impl(corr_impl, b, h, w, dtype, n_dev)
    if corr_impl not in ("volume", "volume_gather", "on_demand", "on_demand_matmul"):
        raise ValueError(
            f"corr_impl must be auto|volume|volume_gather|on_demand|"
            f"on_demand_matmul, got {corr_impl!r}")

    def local(p, fr, fl):  # per-shard: (k, H, W, 3) main + (1, H, W, 3) last
        x = (2.0 * (fr.astype(jnp.float32) / 255.0) - 1.0).astype(dtype)
        xl = (2.0 * (fl.astype(jnp.float32) / 255.0) - 1.0).astype(dtype)
        f_loc = _encoder(p["fnet"], x, "instance").astype(jnp.float32)
        f_extra = _encoder(p["fnet"], xl, "instance").astype(jnp.float32)
        f_next = boundary_from_next(f_loc[:1], f_extra, axis, n_dev)
        f2 = jnp.concatenate([f_loc[1:], f_next], axis=0)
        cnet = _encoder(p["cnet"], x, "batch")  # sources only: no halo needed
        return _refine_flow(p, f_loc, f2, cnet, iters, None, corr_impl, dtype)

    fn = shard_map(local, mesh=mesh,
                   in_specs=(P(), P(axis), P()), out_specs=P(axis))
    return fn(params, frames, frame_last)


def _refine_flow(params: Dict, f1: jnp.ndarray, f2: jnp.ndarray, cnet: jnp.ndarray,
                 iters: int, taps, corr_impl: str, dtype=jnp.float32) -> jnp.ndarray:
    """Shared post-encoder body: correlation pyramid + iterative GRU refinement.

    ``dtype`` drives the motion-encoder/GRU/flow-head convs and the stored
    correlation pyramid (fp32-accumulated, then cast); the coords/flow carry
    stays fp32 regardless — sub-pixel refinement accumulates 20 deltas, and
    bf16's 8 mantissa bits would quantize the carry itself, not just each
    step's conv noise.
    """
    if corr_impl in ("volume", "volume_gather"):
        pyramid = _build_pyramid(f1, f2, dtype)
        impl = "matmul" if corr_impl == "volume" else "gather"
        lookup = lambda coords: _lookup(pyramid, coords, impl)  # noqa: E731
    else:
        f2_pyramid = _build_f2_pyramid(f2)
        od_impl = "matmul" if corr_impl == "on_demand_matmul" else "gather"
        lookup = lambda coords: _lookup_on_demand(  # noqa: E731
            f1, f2_pyramid, coords, od_impl, dtype=dtype)

    net = jnp.tanh(cnet[..., :HIDDEN_DIM]).astype(dtype)
    inp = _relu(cnet[..., HIDDEN_DIM:]).astype(dtype)

    b, h8, w8, _ = f1.shape
    coords0 = coords_grid(b, h8, w8)
    up = params["update_block"]

    if taps is not None:
        taps["fnet1"], taps["fnet2"], taps["cnet"] = f1, f2, cnet
        taps["corr_l0"] = _build_pyramid(f1, f2)[0]

    def body(carry, _):
        net, coords1 = carry
        corr = lookup(coords1).astype(dtype)
        flow = (coords1 - coords0).astype(dtype)
        motion = _motion_encoder(up["encoder"], flow, corr)
        net = _sep_conv_gru(up["gru"], net, jnp.concatenate([inp, motion], -1))
        delta = conv2d(up["flow_head"]["conv2"],
                       _relu(conv2d(up["flow_head"]["conv1"], net, 1, 1)), 1, 1)
        return (net, coords1 + delta.astype(jnp.float32)), None

    if taps is None:
        (net, coords1), _ = lax.scan(body, (net, coords0), None, length=iters)
    else:
        coords1 = coords0
        for it in range(iters):
            (net, coords1), _ = body((net, coords1), None)
            taps[f"flow_iter{it}"] = coords1 - coords0

    mask = 0.25 * conv2d(up["mask.2"], _relu(conv2d(up["mask.0"], net, 1, 1)), 1, 0)
    return _convex_upsample(coords1 - coords0, mask)


def pad_to_multiple(frames: np.ndarray, m: int) -> Tuple[np.ndarray, Tuple[int, int, int, int]]:
    """Replicate-pad (…, H, W, C) to multiples of ``m``, sintel split
    (raft.py:27-39 semantics, generalized from 8 to any bucket size).

    Returns (padded, (top, bottom, left, right)) for :func:`unpad`.
    """
    h, w = frames.shape[-3:-1]
    # delegate to pad_to_shape: the packed loop's byte-parity contract needs
    # the /8 pad and the explicit-bucket pad to be the SAME split forever
    return pad_to_shape(frames, (-(-h // m) * m, -(-w // m) * m))


def pad_to_multiple_of_8(frames: np.ndarray) -> Tuple[np.ndarray, Tuple[int, int, int, int]]:
    """The reference's /8 input pad (raft.py:27-44)."""
    return pad_to_multiple(frames, 8)


def pad_split(h: int, w: int, th: int, tw: int,
              ) -> Tuple[int, int, int, int]:
    """The centered sintel pad split (top, bottom, left, right) taking
    ``h``×``w`` frames to ``th``×``tw`` — the one arithmetic every pad
    variant here (host, in-place, traced) and the unpad slicing share."""
    if th < h or tw < w:
        raise ValueError(f"cannot pad {h}x{w} frames down to bucket {th}x{tw}")
    ph, pw = th - h, tw - w
    return ph // 2, ph - ph // 2, pw // 2, pw - pw // 2


def device_pad_to_shape(x: jnp.ndarray, target_hw: Tuple[int, int],
                        ) -> jnp.ndarray:
    """Traced :func:`pad_to_shape`: replicate-pad (…, H, W, C) to an
    explicit ``(H, W)`` geometry INSIDE the jitted step (``--device_preproc``
    — the host ships RAW decoded frames and the /8-or-bucket pad becomes the
    step's first fused op). Geometry is static at trace time; the same
    centered sintel split as the host pad, on the same wire dtype
    (``jnp.pad(mode="edge")`` replicates values without arithmetic), so the
    padded window is BYTE-identical to ``pad_to_shape`` — pinned by
    tests/test_device_preproc.py, which is why the flag is execution-only
    for the flow extractors (cache/key.py).
    """
    th, tw = target_hw
    h, w = int(x.shape[-3]), int(x.shape[-2])
    top, bottom, left, right = pad_split(h, w, th, tw)
    if not (top or bottom or left or right):
        return x
    pad = [(0, 0)] * (x.ndim - 3) + [(top, bottom), (left, right), (0, 0)]
    return jnp.pad(x, pad, mode="edge")


def pad_to_shape(frames: np.ndarray, target_hw: Tuple[int, int],
                 ) -> Tuple[np.ndarray, Tuple[int, int, int, int]]:
    """Replicate-pad (…, H, W, C) to an explicit ``(H, W)`` bucket geometry.

    Same centered sintel split as :func:`pad_to_multiple` — when the target
    is the geometry's own /8 (or ``--shape_bucket``) padding, the result is
    byte-identical to the per-video path's pad, which is what the packed
    flow loop's byte-parity contract rides on. Dtype-preserving: uint8
    frames pad to uint8 (the wire format — the u8→fp32 cast lives inside
    the jitted step, not here). Returns (padded, pads) for :func:`unpad`.
    """
    th, tw = target_hw
    h, w = frames.shape[-3:-1]
    top, bottom, left, right = pad_split(h, w, th, tw)
    if not (top or bottom or left or right):
        return frames, (0, 0, 0, 0)
    pad = [(0, 0)] * (frames.ndim - 3) + [(top, bottom), (left, right), (0, 0)]
    return np.pad(frames, pad, mode="edge"), (top, bottom, left, right)


def pad_to_shape_into(frame: np.ndarray, out: np.ndarray,
                      ) -> Tuple[int, int, int, int]:
    """:func:`pad_to_shape` into a PREALLOCATED ``(TH, TW, C)`` buffer.

    The staging-ring fast path: one (H, W, C) decoded frame is written
    replicate-padded straight into its row of a reusable device-batch buffer
    — no intermediate ``np.pad`` allocation per frame, and the dtype follows
    ``out`` (uint8 stays uint8; a float32 ring under ``--float32_wire``
    upcasts exactly). Byte-identical to ``pad_to_shape(frame, out.shape[:2])``
    — fill the center, replicate the side columns across the frame's rows,
    then replicate whole padded rows outward (corners land on the frame's
    corner texels, ``np.pad(mode="edge")`` semantics). Returns the same pads
    tuple for :func:`unpad`.
    """
    th, tw = out.shape[0], out.shape[1]
    h, w = frame.shape[0], frame.shape[1]
    top, bottom, left, right = pad_split(h, w, th, tw)
    out[top : th - bottom, left : tw - right] = frame
    if left:
        out[top : th - bottom, :left] = frame[:, :1]
    if right:
        out[top : th - bottom, tw - right :] = frame[:, -1:]
    if top:
        out[:top] = out[top : top + 1]
    if bottom:
        out[th - bottom :] = out[th - bottom - 1 : th - bottom]
    return (top, bottom, left, right)


def unpad(x: np.ndarray, pads: Tuple[int, int, int, int]) -> np.ndarray:
    top, bottom, left, right = pads
    h, w = x.shape[-3:-1]
    return x[..., top : h - bottom, left : w - right, :]


# ---------------------------------------------------------------------------
# Shapes / random init (no torch needed): (cin, cout, kh, kw, pad-implied-by-use)
# ---------------------------------------------------------------------------

def _conv_shapes() -> Dict[str, Tuple[int, int, int, int]]:
    shapes: Dict[str, Tuple[int, int, int, int]] = {}

    def encoder(prefix: str, out_dim: int, batch_norm: bool):
        shapes[f"{prefix}.conv1"] = (3, 64, 7, 7)
        if batch_norm:
            shapes[f"{prefix}.norm1"] = (64,)
        cin = 64
        for stage, dim, stride in (("layer1", 64, 1), ("layer2", 96, 2), ("layer3", 128, 2)):
            for blk in (0, 1):
                s = stride if blk == 0 else 1
                p = f"{prefix}.{stage}.{blk}"
                shapes[f"{p}.conv1"] = (cin if blk == 0 else dim, dim, 3, 3)
                shapes[f"{p}.conv2"] = (dim, dim, 3, 3)
                if batch_norm:
                    shapes[f"{p}.norm1"] = (dim,)
                    shapes[f"{p}.norm2"] = (dim,)
                if blk == 0 and s != 1:
                    shapes[f"{p}.downsample.0"] = (cin, dim, 1, 1)
                    if batch_norm:
                        shapes[f"{p}.norm3"] = (dim,)
            cin = dim
        shapes[f"{prefix}.conv2"] = (128, out_dim, 1, 1)

    encoder("fnet", 256, batch_norm=False)
    encoder("cnet", HIDDEN_DIM + CONTEXT_DIM, batch_norm=True)

    cor_planes = CORR_LEVELS * (2 * CORR_RADIUS + 1) ** 2  # 324
    ub = "update_block"
    shapes[f"{ub}.encoder.convc1"] = (cor_planes, 256, 1, 1)
    shapes[f"{ub}.encoder.convc2"] = (256, 192, 3, 3)
    shapes[f"{ub}.encoder.convf1"] = (2, 128, 7, 7)
    shapes[f"{ub}.encoder.convf2"] = (128, 64, 3, 3)
    shapes[f"{ub}.encoder.conv"] = (192 + 64, 126, 3, 3)
    gru_in = HIDDEN_DIM + 128 + HIDDEN_DIM  # h + (motion 128) + context
    for sfx, k in (("1", (1, 5)), ("2", (5, 1))):
        for gate in ("convz", "convr", "convq"):
            shapes[f"{ub}.gru.{gate}{sfx}"] = (gru_in, HIDDEN_DIM, *k)
    shapes[f"{ub}.flow_head.conv1"] = (HIDDEN_DIM, 256, 3, 3)
    shapes[f"{ub}.flow_head.conv2"] = (256, 2, 3, 3)
    shapes[f"{ub}.mask.0"] = (128, 256, 3, 3)
    shapes[f"{ub}.mask.2"] = (256, 64 * 9, 1, 1)
    return shapes


def raft_init_params(seed: int = 0) -> Dict:
    """Deterministic random param pytree with checkpoint-identical structure."""
    rng = np.random.default_rng(seed)
    tree: Dict = {}

    def put(path, leaf):
        node = tree
        for p in path[:-1]:
            node = node.setdefault(p, {})
        node[path[-1]] = leaf

    for name, shape in _conv_shapes().items():
        path = name.split(".")
        merged = []
        i = 0
        while i < len(path):
            if i + 1 < len(path) and path[i + 1].isdigit():
                merged.append(path[i] + "." + path[i + 1])
                i += 2
            else:
                merged.append(path[i])
                i += 1
        if len(shape) == 1:  # batch norm
            c = shape[0]
            put(merged, {
                "scale": rng.uniform(0.5, 1.5, c).astype(np.float32),
                "bias": (rng.standard_normal(c) * 0.05).astype(np.float32),
                "mean": (rng.standard_normal(c) * 0.05).astype(np.float32),
                "var": rng.uniform(0.5, 1.5, c).astype(np.float32),
            })
        else:
            cin, cout, kh, kw = shape
            put(merged, {
                "kernel": (rng.standard_normal((kh, kw, cin, cout)) * 0.05).astype(np.float32),
                "bias": (rng.standard_normal(cout) * 0.05).astype(np.float32),
            })
    return tree
