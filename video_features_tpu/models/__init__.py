"""Flax model definitions: I3D, R(2+1)D, ResNet-50, RAFT, PWC-Net, VGGish.

All models are inference-first: BatchNorm runs off converted running statistics,
layouts are NHWC/NDHWC (TPU-native), and every forward is shape-static so XLA
compiles it once per input geometry.
"""
