"""R(2+1)D-18 video network in Flax (NDHWC), torchvision ``r2plus1d_18`` numerics.

Behavioral spec — the reference consumes torchvision's pretrained model with the fc
head swapped for identity (``/root/reference/models/r21d/extract_r21d.py:57-62``):
- stem: (1,7,7)/s(1,2,2) conv → BN → ReLU → (3,1,1) conv → BN → ReLU (45 midplanes);
- 4 stages of 2 BasicBlocks; every 3D conv is factored spatial (1,3,3) + BN + ReLU +
  temporal (3,1,1); midplanes ``⌊in·out·27 / (in·9 + 3·out)⌋`` is computed ONCE per
  block from (block_in, cout) and shared by both convs (so conv2 of downsampling
  blocks gets 230/460/921, not a per-conv recomputation); stages 2–4 open with
  stride 2 on both the spatial and temporal factors and a (1,1,1)/2 downsample;
- global average pool → 512-d features (fc applied only for ``--show_pred``).

Module names mirror the torchvision state_dict (``stem.0``, ``layer1.0.conv1.0.0``,
...) so conversion is a pure name/layout map. Channel-last NDHWC: both factored convs
land on the MXU with native tiling.
"""

from __future__ import annotations

from typing import Any, Dict, Tuple

import flax.linen as nn
import jax.numpy as jnp
import numpy as np

from .layers import TorchBatchNorm


def _conv3d(features, kernel, stride, padding, dtype, name):
    """Direct nn.Conv with explicit torch pads for ALL dtypes.

    Unlike I3D's full-3D kernels, R(2+1)D's factored (1,k,k)/(k,1,1) convs are
    NOT hit by the backend's conv3d-bf16 pathology — measured same-run on v5e:
    plain conv3d bf16 91.4 clips/s vs fp32 70.5 (round 2), while routing them
    through the TapConv3D lowering DROPPED bf16 to 72.8 (the strided temporal
    slicing relayout costs more than it saves when kt·kh·kw is already
    factored). I3D keeps conv3d_module; R21D keeps the direct conv.
    """
    return nn.Conv(features, tuple(kernel), strides=tuple(stride),
                    padding=tuple(tuple(p) for p in padding), use_bias=False,
                    dtype=dtype, name=name)

STAGE_CHANNELS = (64, 128, 256, 512)
NUM_FEATURES = 512


def midplanes(cin: int, cout: int) -> int:
    return (cin * cout * 3 * 3 * 3) // (cin * 3 * 3 + 3 * cout)


class Conv2Plus1D(nn.Module):
    """Factored 3D conv: spatial (1,3,3) → BN → ReLU → temporal (3,1,1)."""

    cout: int
    mid: int
    stride: int = 1
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x: jnp.ndarray) -> jnp.ndarray:
        s = self.stride
        x = _conv3d(self.mid, (1, 3, 3), (1, s, s),
                    ((0, 0), (1, 1), (1, 1)), self.dtype, "0")(x)
        x = TorchBatchNorm(dtype=self.dtype, name="1")(x)
        x = nn.relu(x)
        return _conv3d(self.cout, (3, 1, 1), (s, 1, 1),
                       ((1, 1), (0, 0), (0, 0)), self.dtype, "3")(x)


class BasicBlock(nn.Module):
    cin: int
    cout: int
    stride: int = 1
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x: jnp.ndarray) -> jnp.ndarray:
        # torchvision computes midplanes ONCE per block from (inplanes, planes)
        # and passes the same value to both conv_builder calls
        # (torchvision/models/video/resnet.py BasicBlock.__init__); conv2 does
        # NOT recompute from (planes, planes).
        mid = midplanes(self.cin, self.cout)
        y = Conv2Plus1D(self.cout, mid, self.stride, self.dtype, name="conv1.0")(x)
        y = TorchBatchNorm(dtype=self.dtype, name="conv1.1")(y)
        y = nn.relu(y)
        y = Conv2Plus1D(self.cout, mid, 1, self.dtype, name="conv2.0")(y)
        y = TorchBatchNorm(dtype=self.dtype, name="conv2.1")(y)
        if self.stride != 1 or self.cin != self.cout:
            x = _conv3d(self.cout, (1, 1, 1), (self.stride,) * 3,
                        ((0, 0), (0, 0), (0, 0)), self.dtype, "downsample.0")(x)
            x = TorchBatchNorm(dtype=self.dtype, name="downsample.1")(x)
        return nn.relu(x + y)


class R2Plus1D18(nn.Module):
    """Input NDHWC (B, T, H, W, 3) float, Kinetics-normalized."""

    num_classes: int = 400
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x: jnp.ndarray, features: bool = True) -> jnp.ndarray:
        x = x.astype(self.dtype)
        x = _conv3d(45, (1, 7, 7), (1, 2, 2),
                    ((0, 0), (3, 3), (3, 3)), self.dtype, "stem.0")(x)
        x = TorchBatchNorm(dtype=self.dtype, name="stem.1")(x)
        x = nn.relu(x)
        x = _conv3d(64, (3, 1, 1), (1, 1, 1),
                    ((1, 1), (0, 0), (0, 0)), self.dtype, "stem.3")(x)
        x = TorchBatchNorm(dtype=self.dtype, name="stem.4")(x)
        x = nn.relu(x)

        cin = 64
        for stage, cout in enumerate(STAGE_CHANNELS, start=1):
            for blk in range(2):
                stride = 2 if (stage > 1 and blk == 0) else 1
                x = BasicBlock(cin, cout, stride, self.dtype, name=f"layer{stage}.{blk}")(x)
                cin = cout

        x = jnp.mean(x, axis=(1, 2, 3))  # adaptive avg pool (1,1,1) → (B, 512)
        if features:
            return x
        return nn.Dense(self.num_classes, dtype=self.dtype, name="fc")(x)


KINETICS_MEAN = (0.43216, 0.394666, 0.37645)
KINETICS_STD = (0.22803, 0.22145, 0.216989)
PRE_CROP_SIZE = (128, 171)
CROP_SIZE = 112


def r21d_preprocess(frames_u8: jnp.ndarray, dtype=jnp.float32) -> jnp.ndarray:
    """uint8 (T, H, W, 3) native-resolution frames → (T, 112, 112, 3) normalized.

    Reference transform stack in order (``extract_r21d.py:32-38``):
    ``ToFloatTensorInZeroOne`` (/255) → ``Resize((128,171))`` (bilinear,
    align_corners=False) → Kinetics ``Normalize`` → ``CenterCrop(112)``
    (round-half offsets, ``rgb_transforms.py:14-20``). Runs on device so XLA fuses
    it into the stem convs.
    """
    from ..ops.warp import resize_bilinear_torch

    x = frames_u8.astype(jnp.float32) / 255.0
    x = resize_bilinear_torch(x, *PRE_CROP_SIZE)
    x = ((x - jnp.asarray(KINETICS_MEAN, jnp.float32))
         / jnp.asarray(KINETICS_STD, jnp.float32))
    h, w = x.shape[-3], x.shape[-2]
    i = int(round((h - CROP_SIZE) / 2.0))
    j = int(round((w - CROP_SIZE) / 2.0))
    return x[..., i : i + CROP_SIZE, j : j + CROP_SIZE, :].astype(dtype)


def r21d_conv_shapes() -> Dict[str, Tuple]:
    """torch-layout shapes keyed by state_dict prefix: conv (O,I,kt,kh,kw),
    'bn' → (C,), fc → (O, I). Shared by the random init and the torch mirror."""
    shapes: Dict[str, Tuple] = {
        "stem.0": (45, 3, 1, 7, 7), "stem.1": ("bn", 45),
        "stem.3": (64, 45, 3, 1, 1), "stem.4": ("bn", 64),
    }
    cin = 64
    for stage, cout in enumerate(STAGE_CHANNELS, start=1):
        for blk in range(2):
            p = f"layer{stage}.{blk}"
            block_in = cin if blk == 0 else cout
            # one midplanes per block, shared by conv1 and conv2 (torchvision)
            mid = midplanes(block_in, cout)
            shapes[f"{p}.conv1.0.0"] = (mid, block_in, 1, 3, 3)
            shapes[f"{p}.conv1.0.1"] = ("bn", mid)
            shapes[f"{p}.conv1.0.3"] = (cout, mid, 3, 1, 1)
            shapes[f"{p}.conv1.1"] = ("bn", cout)
            shapes[f"{p}.conv2.0.0"] = (mid, cout, 1, 3, 3)
            shapes[f"{p}.conv2.0.1"] = ("bn", mid)
            shapes[f"{p}.conv2.0.3"] = (cout, mid, 3, 1, 1)
            shapes[f"{p}.conv2.1"] = ("bn", cout)
            if blk == 0 and stage > 1:
                shapes[f"{p}.downsample.0"] = (cout, block_in, 1, 1, 1)
                shapes[f"{p}.downsample.1"] = ("bn", cout)
        cin = cout
    shapes["fc"] = (400, 512)
    return shapes
