"""VGGish audio embedding network in Flax (NHWC) + PCA postprocessor.

Behavioral spec — ``/root/reference/models/vggish/vggish_src/vggish_slim.py:39-99``:
input (N, 96, 64) log-mel patches → reshape (N, 96, 64, 1) → VGG stack
(conv3x3 SAME + ReLU: 64 → pool → 128 → pool → 256×2 → pool → 512×2 → pool) →
flatten → fc 4096 → fc 4096 → fc 128 (all ReLU, including the embedding layer —
slim's arg_scope applies relu to fc2 as well). All pools 2×2/2 SAME.

With the fixed 96×64 geometry every pool divides exactly, so SAME == VALID here
and the flatten is (N, 6, 4, 512) row-major — matching TF's NHWC flatten, which is
what the checkpoint's fc weights were trained against.

The PCA postprocessor (``vggish_postprocess.py:52-91``) is implemented and wired
but OFF by default: the reference instantiates it and never applies it
(``extract_vggish.py:57,104-116`` — SURVEY.md §2.1 #19), so default outputs match.

Param tree follows TF variable naming under ``vggish/`` (conv1, conv3/conv3_1,
fc1/fc1_1, ...) so an npz exported from the TF checkpoint converts by name.
"""

from __future__ import annotations

from typing import Any, Dict, Mapping

import flax.linen as nn
import jax.numpy as jnp
import numpy as np

NUM_FRAMES = 96
NUM_BANDS = 64
EMBEDDING_SIZE = 128


class VGGish(nn.Module):
    """Input (N, 96, 64) or (N, 96, 64, 1) float log-mel patches → (N, 128)."""

    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x: jnp.ndarray) -> jnp.ndarray:
        if x.ndim == 3:
            x = x[..., None]
        x = x.astype(self.dtype)

        def conv(name, features, y):
            y = nn.Conv(features, (3, 3), padding="SAME", dtype=self.dtype, name=name)(y)
            return nn.relu(y)

        def pool(y):
            return nn.max_pool(y, (2, 2), strides=(2, 2), padding="SAME")

        x = pool(conv("conv1", 64, x))
        x = pool(conv("conv2", 128, x))
        x = pool(conv("conv3_2", 256, conv("conv3_1", 256, x)))
        x = pool(conv("conv4_2", 512, conv("conv4_1", 512, x)))

        x = x.reshape((x.shape[0], -1))  # NHWC row-major flatten, TF-compatible
        x = nn.relu(nn.Dense(4096, dtype=self.dtype, name="fc1_1")(x))
        x = nn.relu(nn.Dense(4096, dtype=self.dtype, name="fc1_2")(x))
        # slim's arg_scope puts ReLU on the embedding layer too (vggish_slim.py:65-67)
        x = nn.relu(nn.Dense(EMBEDDING_SIZE, dtype=self.dtype, name="fc2")(x))
        return x.astype(jnp.float32)


def convert_tf_vggish(tf_vars: Mapping[str, np.ndarray]) -> Dict:
    """TF checkpoint variables (``vggish/conv1/weights`` HWIO, ``.../biases``) →
    Flax param tree. Accepts names with or without the ``vggish/`` scope prefix.

    TF conv kernels are already HWIO and fc kernels (in, out) — no transposes;
    the TF scope path collapses to the leaf module name (``conv3/conv3_1`` →
    ``conv3_1``).
    """
    params: Dict = {}
    for name, value in tf_vars.items():
        key = name[len("vggish/"):] if name.startswith("vggish/") else name
        key = key.replace(":0", "")
        *scope, leaf = key.split("/")
        module = scope[-1]  # conv3/conv3_1 → conv3_1; conv1 → conv1
        leaf = {"weights": "kernel", "biases": "bias"}[leaf]
        params.setdefault(module, {})[leaf] = np.asarray(value)
    return params


def vggish_init_params(seed: int = 0) -> Dict:
    """Deterministic random params (the TF init is N(0, 0.01) — vggish_params.py)."""
    rng = np.random.default_rng(seed)
    shapes = {
        "conv1": (3, 3, 1, 64), "conv2": (3, 3, 64, 128),
        "conv3_1": (3, 3, 128, 256), "conv3_2": (3, 3, 256, 256),
        "conv4_1": (3, 3, 256, 512), "conv4_2": (3, 3, 512, 512),
        "fc1_1": (6 * 4 * 512, 4096), "fc1_2": (4096, 4096),
        "fc2": (4096, EMBEDDING_SIZE),
    }
    return {
        name: {
            "kernel": (rng.standard_normal(shape) * 0.01).astype(np.float32),
            "bias": np.zeros(shape[-1], np.float32),
        }
        for name, shape in shapes.items()
    }


class Postprocessor:
    """PCA-whiten + clip [−2, 2] + uint8 quantize (``vggish_postprocess.py:52-91``).

    ``params_npz`` holds ``pca_eigen_vectors`` (128, 128) and ``pca_means`` (128,)
    — the file the reference ships at ``models/vggish/checkpoints/
    vggish_pca_params.npz``.
    """

    QUANTIZE_MIN = -2.0
    QUANTIZE_MAX = 2.0

    def __init__(self, params_npz: str):
        with np.load(params_npz) as z:
            self.eigen_vectors = z["pca_eigen_vectors"].astype(np.float64)
            self.means = z["pca_means"].reshape(-1, 1).astype(np.float64)
        if self.eigen_vectors.shape != (EMBEDDING_SIZE, EMBEDDING_SIZE):
            raise ValueError(f"bad pca_eigen_vectors shape {self.eigen_vectors.shape}")
        if self.means.shape != (EMBEDDING_SIZE, 1):
            raise ValueError(f"bad pca_means shape {self.means.shape}")

    def postprocess(self, embeddings: np.ndarray) -> np.ndarray:
        """(N, 128) float → (N, 128) uint8."""
        applied = (self.eigen_vectors @ (embeddings.T.astype(np.float64) - self.means)).T
        clipped = np.clip(applied, self.QUANTIZE_MIN, self.QUANTIZE_MAX)
        quantized = (clipped - self.QUANTIZE_MIN) * (
            255.0 / (self.QUANTIZE_MAX - self.QUANTIZE_MIN)
        )
        return quantized.astype(np.uint8)
