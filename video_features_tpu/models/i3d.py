"""Inception-3D (I3D, Kinetics-400) in Flax, NDHWC layout.

Behavioral spec — ``/root/reference/models/i3d/i3d_src/i3d_net.py``:
- Unit3D = conv3d (TF-SAME asymmetric padding, no bias) + eval BatchNorm + ReLU
  (``:37-105``); max pools zero-pad TF-SAME then pool with ceil_mode (``:108-120``).
- Stem conv 7³/2, two 1×3×3/1×2×2 pools, conv 1³, conv 3³, then nine Inception
  ``Mixed`` blocks with a 3³/2³ pool between groups (``:179-224``).
- Features head (``features=True``): AvgPool3d (2,7,7) stride 1 → squeeze spatial →
  mean over remaining time → (B, 1024) (``:257-264``).
- Logits head: 1³ conv with bias (no BN/ReLU) → squeeze → time mean → softmax;
  returns (probs, logits) (``:266-274``).
- ``modality``: 'rgb' (3 input channels) or 'flow' (2) (``:170-176``).

TPU design: channel-last NDHWC so every conv lands on the MXU with native tiling;
the asymmetric SAME pads are explicit ``lax.conv_general_dilated`` padding (no
separate pad op to fuse away); the architecture is one spec table walked by
``nn.compact`` — module names match the reference state_dict so checkpoint
conversion is a pure name/layout map.
"""

from __future__ import annotations

from typing import Any, Sequence, Tuple

import flax.linen as nn
import jax.numpy as jnp

from .layers import (
    S2DStemConv,
    TorchBatchNorm,
    avg_pool_valid,
    conv3d_module,
    max_pool_tf_same,
    tf_same_pads,
)

# (branch_0) (branch_1 reduce, branch_1 out) (branch_2 reduce, branch_2 out) (branch_3)
MixedSpec = Tuple[int, int, int, int, int, int]

# name → op spec; walked in order by I3D.__call__
I3D_STEM = (
    ("conv", "conv3d_1a_7x7", 64, (7, 7, 7), (2, 2, 2)),
    ("pool", "maxPool3d_2a_3x3", (1, 3, 3), (1, 2, 2)),
    ("conv", "conv3d_2b_1x1", 64, (1, 1, 1), (1, 1, 1)),
    ("conv", "conv3d_2c_3x3", 192, (3, 3, 3), (1, 1, 1)),
    ("pool", "maxPool3d_3a_3x3", (1, 3, 3), (1, 2, 2)),
    ("mixed", "mixed_3b", (64, 96, 128, 16, 32, 32)),
    ("mixed", "mixed_3c", (128, 128, 192, 32, 96, 64)),
    ("pool", "maxPool3d_4a_3x3", (3, 3, 3), (2, 2, 2)),
    ("mixed", "mixed_4b", (192, 96, 208, 16, 48, 64)),
    ("mixed", "mixed_4c", (160, 112, 224, 24, 64, 64)),
    ("mixed", "mixed_4d", (128, 128, 256, 24, 64, 64)),
    ("mixed", "mixed_4e", (112, 144, 288, 32, 64, 64)),
    ("mixed", "mixed_4f", (256, 160, 320, 32, 128, 128)),
    ("pool", "maxPool3d_5a_2x2", (2, 2, 2), (2, 2, 2)),
    ("mixed", "mixed_5b", (256, 160, 320, 32, 128, 128)),
    ("mixed", "mixed_5c", (384, 192, 384, 48, 128, 128)),
)

NUM_FEATURES = 1024


class Unit3D(nn.Module):
    """conv3d + (optional) BN + (optional) ReLU with reference TF-SAME padding."""

    features: int
    kernel: Sequence[int] = (1, 1, 1)
    stride: Sequence[int] = (1, 1, 1)
    use_bn: bool = True
    use_bias: bool = False
    relu: bool = True
    s2d: bool = False  # space-to-depth lowering (7³/2³ stem only, see layers.py)
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x: jnp.ndarray) -> jnp.ndarray:
        if self.s2d:
            assert tuple(self.kernel) == (7, 7, 7) and tuple(self.stride) == (2, 2, 2)
            assert not self.use_bias
            x = S2DStemConv(self.features, dtype=self.dtype, name="conv3d")(x)
        elif not self.use_bias:
            # shared chooser: bf16 takes the TapConv3D lowering (conv3d-bf16
            # backend pathology), fp32 the direct conv — same param tree
            x = conv3d_module(self.features, self.kernel, self.stride,
                              tf_same_pads(self.kernel, self.stride),
                              self.dtype, "conv3d")(x)
        else:
            x = nn.Conv(
                self.features,
                tuple(self.kernel),
                strides=tuple(self.stride),
                padding=tf_same_pads(self.kernel, self.stride),
                use_bias=True,
                dtype=self.dtype,
                name="conv3d",
            )(x)
        if self.use_bn:
            x = TorchBatchNorm(dtype=self.dtype, name="batch3d")(x)
        if self.relu:
            x = nn.relu(x)
        return x


class Mixed(nn.Module):
    """Inception block: 1³ | 1³→3³ | 1³→3³ | pool→1³, concatenated on channels.

    Submodule names mirror the reference state_dict (``branch_1.0`` etc.,
    ``i3d_net.py:123-157``) so conversion needs no name table.
    """

    spec: MixedSpec
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x: jnp.ndarray) -> jnp.ndarray:
        c0, c1r, c1, c2r, c2, c3 = self.spec
        dt = self.dtype
        b0 = Unit3D(c0, dtype=dt, name="branch_0")(x)
        b1 = Unit3D(c1r, dtype=dt, name="branch_1.0")(x)
        b1 = Unit3D(c1, (3, 3, 3), dtype=dt, name="branch_1.1")(b1)
        b2 = Unit3D(c2r, dtype=dt, name="branch_2.0")(x)
        b2 = Unit3D(c2, (3, 3, 3), dtype=dt, name="branch_2.1")(b2)
        b3 = max_pool_tf_same(x, (3, 3, 3), (1, 1, 1))
        b3 = Unit3D(c3, dtype=dt, name="branch_3.1")(b3)
        return jnp.concatenate([b0, b1, b2, b3], axis=-1)


class I3D(nn.Module):
    """Input NDHWC float in [-1, 1]; (B, T, H, W, 3) rgb or (B, T, H, W, 2) flow."""

    num_classes: int = 400
    modality: str = "rgb"
    s2d_stem: bool = False  # MXU space-to-depth stem (fp-reassociation only)
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x: jnp.ndarray, features: bool = True):
        expected_c = {"rgb": 3, "flow": 2}[self.modality]
        if x.shape[-1] != expected_c:
            raise ValueError(
                f"{self.modality} I3D expects {expected_c} input channels, got {x.shape[-1]}"
            )
        x = x.astype(self.dtype)
        for op, name, *spec in I3D_STEM:
            if op == "conv":
                feats, kernel, stride = spec
                s2d = self.s2d_stem and name == "conv3d_1a_7x7"
                x = Unit3D(feats, kernel, stride, s2d=s2d, dtype=self.dtype, name=name)(x)
            elif op == "pool":
                kernel, stride = spec
                x = max_pool_tf_same(x, kernel, stride)
            else:
                x = Mixed(spec[0], dtype=self.dtype, name=name)(x)

        # (B, T', 7, 7, 1024) → AvgPool3d((2,7,7), stride 1) → (B, T'-1, 1, 1, 1024).
        # The reference kernel (2,7,7) assumes the 224-crop geometry where the final
        # spatial size is exactly 7×7; the spatial kernel adapts so smaller (test)
        # inputs work — identical numerics at the supported 224 input.
        if x.shape[1] < 2:
            raise ValueError(
                f"input too short for I3D: {x.shape[1]} temporal positions remain "
                f"before the (2,·,·) average pool; use stack_size >= 16"
            )
        x = avg_pool_valid(x.astype(jnp.float32), (2, x.shape[2], x.shape[3]), (1, 1, 1))
        if features:
            return jnp.mean(x[:, :, 0, 0, :], axis=1)  # (B, 1024)

        logits = Unit3D(
            self.num_classes,
            use_bn=False,
            use_bias=True,
            relu=False,
            dtype=jnp.float32,
            name="conv3d_0c_1x1",
        )(x)
        logits = jnp.mean(logits[:, :, 0, 0, :], axis=1)  # (B, num_classes)
        return nn.softmax(logits, axis=-1), logits


def i3d_preprocess_rgb(frames_u8: jnp.ndarray, dtype=jnp.float32) -> jnp.ndarray:
    """uint8 (B, T, H, W, 3) → [-1, 1] float: the reference ``ScaleTo1_1``
    ((2x/255) − 1, ``models/i3d/transforms/transforms.py``)."""
    return (2.0 * frames_u8.astype(jnp.float32) / 255.0 - 1.0).astype(dtype)


def i3d_preprocess_flow(flow: jnp.ndarray, dtype=jnp.float32) -> jnp.ndarray:
    """Raw flow (B, T, H, W, 2) → clamp ±20 → uint8 quantize → [-1, 1].

    Reference sandwich (``extract_i3d.py:59-72`` + ``transforms.py:43-51``):
    ``Clamp(-20, 20)`` → ``ToUInt8`` = round(128 + 255/40·f), round-half-to-even and
    deliberately *not* clipped (a +20 flow maps to 255.5 → 256) → ``ScaleTo1_1``.
    The quantization is part of how the pretrained flow stream was trained, so it is
    reproduced exactly, quirk included.
    """
    f = jnp.clip(flow.astype(jnp.float32), -20.0, 20.0)
    q = jnp.round(128.0 + 255.0 / 40.0 * f)
    return (2.0 * q / 255.0 - 1.0).astype(dtype)
