"""ResNet-50 in Flax (NHWC), matching torchvision's ``resnet50`` numerics.

The reference consumes torchvision's pretrained ResNet-50 with the classifier head
swapped for identity and kept aside for ``--show_pred``
(``/root/reference/models/resnet50/extract_resnet50.py:54-58``). This module defines
the same architecture TPU-natively: NHWC layout so convs tile straight onto the MXU,
inference-mode BatchNorm (running statistics are parameters), and a ``features``
switch mirroring the identity-head behavior — ``features=True`` returns the 2048-d
global-average-pooled embedding, ``features=False`` additionally applies the fc head
and returns logits.

Param tree follows torchvision naming (``conv1``, ``bn1``, ``layer1.0.conv2``, ...)
so checkpoint conversion (:mod:`video_features_tpu.weights.convert_torch`) is a pure
name-and-layout map.
"""

from __future__ import annotations

from typing import Any, Sequence

import flax.linen as nn
import jax.numpy as jnp

BN_EPS = 1e-5  # torch.nn.BatchNorm2d default


class TorchBatchNorm(nn.Module):
    """Inference BatchNorm with torch semantics: y = (x-mean)/sqrt(var+eps)*scale+bias.

    Running statistics live in the ``params`` collection (they are converted weights,
    never updated), which keeps the whole model a single frozen pytree.
    """

    eps: float = BN_EPS
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x: jnp.ndarray) -> jnp.ndarray:
        c = x.shape[-1]
        scale = self.param("scale", nn.initializers.ones, (c,), jnp.float32)
        bias = self.param("bias", nn.initializers.zeros, (c,), jnp.float32)
        mean = self.param("mean", nn.initializers.zeros, (c,), jnp.float32)
        var = self.param("var", nn.initializers.ones, (c,), jnp.float32)
        # compute the affine in fp32 then cast: matches torch eval-mode numerics
        inv = jnp.asarray(scale, jnp.float32) / jnp.sqrt(jnp.asarray(var, jnp.float32) + self.eps)
        y = (x.astype(jnp.float32) - mean) * inv + bias
        return y.astype(self.dtype)


def max_pool_torch(x: jnp.ndarray, window: int, stride: int, padding: int) -> jnp.ndarray:
    """torch ``MaxPool2d(window, stride, padding)`` on NHWC (pads with -inf)."""
    return nn.max_pool(
        x,
        (window, window),
        strides=(stride, stride),
        padding=[(padding, padding), (padding, padding)],
    )


class Bottleneck(nn.Module):
    """torchvision Bottleneck (v1.5: stride on the 3x3 conv)."""

    planes: int
    stride: int = 1
    downsample: bool = False
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x: jnp.ndarray) -> jnp.ndarray:
        identity = x
        out = nn.Conv(self.planes, (1, 1), use_bias=False, dtype=self.dtype, name="conv1")(x)
        out = TorchBatchNorm(dtype=self.dtype, name="bn1")(out)
        out = nn.relu(out)
        out = nn.Conv(
            self.planes, (3, 3), strides=(self.stride, self.stride),
            padding=[(1, 1), (1, 1)], use_bias=False, dtype=self.dtype, name="conv2",
        )(out)
        out = TorchBatchNorm(dtype=self.dtype, name="bn2")(out)
        out = nn.relu(out)
        out = nn.Conv(self.planes * 4, (1, 1), use_bias=False, dtype=self.dtype, name="conv3")(out)
        out = TorchBatchNorm(dtype=self.dtype, name="bn3")(out)
        if self.downsample:
            identity = nn.Conv(
                self.planes * 4, (1, 1), strides=(self.stride, self.stride),
                use_bias=False, dtype=self.dtype, name="downsample.0",
            )(x)
            identity = TorchBatchNorm(dtype=self.dtype, name="downsample.1")(identity)
        return nn.relu(out + identity)


class ResNet50(nn.Module):
    """torchvision ``resnet50`` architecture; input NHWC float, ImageNet-normalized."""

    num_classes: int = 1000
    dtype: Any = jnp.float32
    stage_sizes: Sequence[int] = (3, 4, 6, 3)

    @nn.compact
    def __call__(self, x: jnp.ndarray, features: bool = True) -> jnp.ndarray:
        x = x.astype(self.dtype)
        x = nn.Conv(64, (7, 7), strides=(2, 2), padding=[(3, 3), (3, 3)],
                    use_bias=False, dtype=self.dtype, name="conv1")(x)
        x = TorchBatchNorm(dtype=self.dtype, name="bn1")(x)
        x = nn.relu(x)
        x = max_pool_torch(x, 3, 2, 1)

        planes = 64
        for stage, blocks in enumerate(self.stage_sizes, start=1):
            for b in range(blocks):
                stride = 2 if (stage > 1 and b == 0) else 1
                x = Bottleneck(
                    planes=planes, stride=stride, downsample=(b == 0),
                    dtype=self.dtype, name=f"layer{stage}.{b}",
                )(x)
            planes *= 2

        x = jnp.mean(x, axis=(1, 2))  # global average pool → (N, 2048)
        if features:
            return x
        return nn.Dense(self.num_classes, dtype=self.dtype, name="fc")(x)


IMAGENET_MEAN = (0.485, 0.456, 0.406)
IMAGENET_STD = (0.229, 0.224, 0.225)


def preprocess_frames(frames_u8_nhwc: jnp.ndarray, dtype=jnp.float32) -> jnp.ndarray:
    """uint8 NHWC (already resized+cropped on host) → normalized float NHWC.

    Reference transform stack: ``ToTensor`` (/255) + ImageNet ``Normalize``
    (``extract_resnet50.py:32-38``). Runs on device inside the jitted forward so XLA
    fuses it into the first conv.
    """
    x = frames_u8_nhwc.astype(jnp.float32) / 255.0
    mean = jnp.asarray(IMAGENET_MEAN, jnp.float32)
    std = jnp.asarray(IMAGENET_STD, jnp.float32)
    return ((x - mean) / std).astype(dtype)
