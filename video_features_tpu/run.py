"""Extract features from videos — TPU-native CLI driver.

Drop-in surface of the reference ``main.py`` (same flags), invoked via the repo's
``main.py`` shim or the ``video-features-tpu`` console script::

    python main.py --feature_type i3d --video_paths a.mp4 b.mp4 --on_extraction save_numpy

Videos are embarrassingly parallel: the list is processed by the extractor, whose
device step is jit-compiled for the local TPU mesh; multi-host jobs shard the list
round-robin per host (``--num_devices`` governs the local mesh size).

Exit codes: 0 — every video succeeded; 1 — some videos failed (classified records
in the failure manifest, reprocess with ``--retry_failed``) or the video list was
empty; 2 — the run aborted before processing the full list: the ``--max_failures``
circuit breaker tripped, or the invocation was invalid (``--retry_failed`` on a
multi-host job; argparse flag errors also exit 2). See docs/reliability.md.

``--serve`` runs the always-on extraction service instead (ingest queue,
tenant scheduler, continuous-batching daemon — docs/serving.md): exit 0 after
a clean drain, 1 when some videos terminally failed, 2 on invalid invocation.
"""

import os
import sys

from video_features_tpu.cli import parse_args
from video_features_tpu.extractors import get_extractor


def _honor_jax_platforms_env() -> None:
    """Make ``JAX_PLATFORMS=cpu python main.py ...`` work under this image.

    The image's sitecustomize registers the axon TPU backend and pins
    ``jax_platforms`` before user code runs, so the env var set by the user on the
    command line is silently ignored unless re-applied through the config API.
    """
    want = os.environ.get("JAX_PLATFORMS")
    if want:
        import jax

        try:
            jax.config.update("jax_platforms", want)
        except Exception as e:  # fault-barrier: best-effort env shim; warn and continue
            print(f"warning: could not apply JAX_PLATFORMS={want}: {e}", file=sys.stderr)


def main(argv=None) -> int:
    _honor_jax_platforms_env()
    cfg = parse_args(argv)

    if cfg.serve:
        # the always-on extraction service (docs/serving.md): single-host by
        # design — the spool/socket ingest and the shared manifests assume
        # one process owns the output tree
        from video_features_tpu.serve import serve

        return serve(cfg)

    # Multi-host bootstrap (DCN): must precede the first device access so every
    # process sees the global topology; no-op on single-host jobs.
    from video_features_tpu.parallel import maybe_initialize_distributed

    if maybe_initialize_distributed():
        import jax

        print(f"multi-host job: process {jax.process_index()}/{jax.process_count()}")

    extractor = get_extractor(cfg)
    if cfg.retry_failed:
        # reprocess exactly the failure-manifest set; each video's record is
        # pruned as it succeeds (an interrupted retry run loses no records)
        # and re-appends only if it fails again. Single-host only — enforced,
        # because concurrent per-host manifest rewrites would clobber records.
        import jax

        from video_features_tpu.reliability import load_failures

        if jax.process_count() > 1:
            print("--retry_failed is single-host only: concurrent hosts "
                  "rewriting the shared failure manifest would lose records. "
                  "Run it from one host (it processes only the failed set).",
                  file=sys.stderr)
            return 2
        paths = sorted(load_failures(extractor.output_dir))
        if not paths:
            print("No failed videos to retry (failure manifest is empty).")
            return 0
        print(f"--retry_failed: reprocessing {len(paths)} video(s) from the failure manifest")
    else:
        paths = extractor.video_list()
    if not paths:
        print("No videos to process.")
        return 1

    # Multi-host jobs: each process owns a round-robin shard of the video list
    # (the reference's gen_file_list.py split, without the manual file juggling).
    from video_features_tpu.parallel import shard_video_list

    paths = shard_video_list(paths)
    if not paths:
        print("No videos assigned to this host.")
        return 0

    def progress(done, total):
        print(f"\r[{done}/{total}] videos processed", end="", flush=True)

    from video_features_tpu.reliability import CircuitBreakerTripped, failed_manifest_path

    try:
        ok = extractor.run(paths, progress=progress)
    except CircuitBreakerTripped as e:
        print()
        print(f"aborted: {e}")
        return 2
    print()
    # --pack_corpus: how full the dispatched device batches actually were
    # (real clips / device slots; the per-video loop's tail padding is the
    # baseline this should beat on short-clip corpora)
    stats = getattr(extractor, "_pack_stats", None)
    if stats and stats.get("dispatched_slots"):
        print(f"packing occupancy: {stats['real_slots']}/"
              f"{stats['dispatched_slots']} device slots "
              f"({stats['occupancy']:.1%})")
        buckets = stats.get("buckets") or {}
        if len(buckets) > 1:  # mixed-geometry corpus: per-bucket accounting
            for name, b in buckets.items():
                print(f"  bucket {name}: {b['real_slots']}/"
                      f"{b['dispatched_slots']} slots "
                      f"({b['occupancy']:.1%}, "
                      f"stale_flushes={b['stale_flushes']})")
    # --cache_dir: how much work the content-addressed feature cache saved
    # (a hit = zero decode + zero device steps; docs/caching.md)
    cache = getattr(extractor, "_cache", None)
    if cache is not None:
        s = cache.stats()
        line = (f"feature cache: {s['hits']} hit(s) / {s['misses']} miss(es) "
                f"({s['hit_rate']:.1%} hit rate), "
                f"{s['hit_bytes'] / 1e6:.1f} MB served, "
                f"{s['puts']} published")
        if s["evictions"] or s["quarantined"]:
            line += (f", {s['evictions']} evicted, "
                     f"{s['quarantined']} quarantined")
        print(line)
    # --telemetry_dir: where the span journal landed and whether the bounded
    # writer had to drop events (docs/observability.md)
    journal = getattr(extractor, "_journal", None)
    if journal is not None:
        s = journal.stats()
        line = (f"telemetry: {s['written']} event(s) journaled to "
                f"{journal.path}")
        if s["dropped"]:
            line += f", {s['dropped']} dropped (bounded queue)"
        if s["write_errors"]:
            line += f", {s['write_errors']} write error(s)"
        print(line)
        print("  view:  python -m video_features_tpu.obs.export "
              f"{journal.path}")
    failed = len(paths) - ok
    if failed:
        print(f"{failed} video(s) failed; classified records in "
              f"{failed_manifest_path(extractor.output_dir)} "
              "(rerun with --retry_failed after fixing the cause)")
    return 0 if failed == 0 else 1


if __name__ == "__main__":
    sys.exit(main())
