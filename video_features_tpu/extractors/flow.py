"""RAFT / PWC dense-flow extractors: one shared frame-pair pipeline.

Behavioral spec (``/root/reference/models/raft/extract_raft.py``,
``.../pwc/extract_pwc.py`` — the loops are copies of each other):
- decode → optional ``--side_size`` PIL edge resize (``extract_raft.py:32-41``);
- accumulate ``batch_size + 1`` frames, flow for consecutive pairs
  ``batch[:-1] → batch[1:]``, carry the last frame into the next batch, run a final
  partial batch of ≥ 2 frames (``:139-151``);
- RAFT pads frames to /8 (replicate, sintel) and unpads the flow (``:94-101``);
  PWC-Net handles arbitrary sizes internally (/64 resize in-model);
- outputs ``(T-1, 2, H, W)`` float32 flow + fps + per-frame timestamps;
- ``--show_pred`` displays frame + color-wheel flow (``:165-178``).

TPU design: pairs are batched into one jitted call with a static pair count (the
tail batch is padded by repeating its last pair, then trimmed), so each video
geometry compiles exactly once; host decode overlaps device compute through the
prefetcher. Frames ride the wire as decoded **uint8** (per-video windows, the
packed collate chains, and the ``--show_pred`` fallback alike): the u8→fp32
scale is the jitted step's first fused op — an exact cast, so outputs are
byte-identical to the retired float32 host staging at a quarter of the
host→device bytes (``--float32_wire`` restores it as an A/B escape hatch) —
and windows are assembled into reusable staging-ring buffers
(:class:`..parallel.pipeline.HostStagingRing`) instead of fresh per-batch
``np.stack`` allocations. Dense flow is the framework's only D2H-heavy output
(full-res fp32 maps, not embeddings — ``extract_raft.py:99-101``); the e2e
pipeline double-buffers the fetch (``copy_to_host_async`` + a bounded pending
queue, so transfer overlaps both compute and decode) and
``--transfer_dtype float16`` halves the bytes on the wire (cast on device,
upcast on host; outputs stay fp32 ``.npy``).

``--device_preproc`` moves the last host-side preprocess — the /8 (RAFT) or
``--shape_bucket`` replicate pad — inside the jitted step
(``models/raft.device_pad_to_shape``): windows stage and ride the wire at RAW
decoded geometry and the pad runs on the uint8 wire as the step's first fused
op. Replication on integers is arithmetic-free, so outputs stay BYTE-identical
to the host pad (pinned in tests/test_device_preproc.py) — the flag is
execution-only for flow in cache/key.py. Each pad target memoizes its own
jitted step (``_frames_step_for``) so a raw geometry can never reuse a program
traced for a different bucket.
"""

from __future__ import annotations

import functools
import os
import threading
from collections import deque
from typing import Dict, List

import numpy as np

from ..models.raft import (
    device_pad_to_shape,
    pad_split,
    pad_to_multiple,
    pad_to_shape,
    pad_to_shape_into,
    raft_forward,
    raft_forward_frames,
    raft_forward_frames_sharded,
    raft_init_params,
    unpad,
)
from ..ops.image import edge_resize_size, pil_edge_resize
from ..weights.convert_torch import convert_raft
from ..weights.store import resolve_params
from .base import Extractor


class ExtractFlow(Extractor):
    """feature_type 'raft' or 'pwc'; emits dense flow frames, not embeddings."""

    uses_frame_stream = True
    # --device_preproc: the geometry pad moves inside the jitted step (raw
    # decoded frames on the wire; device_pad_to_shape is byte-exact vs the
    # host pad). The optional --side_size edge resize stays host PIL — it is
    # a parity-bearing reference transform, not padding.
    supports_device_preproc = True

    def __init__(self, cfg):
        super().__init__(cfg)
        import jax.numpy as jnp

        # pairs per device step, rounded to a multiple of the mesh size so the
        # sharded pair axis divides evenly (tail pairs repeat the last frame)
        self.batch_size = self.runner.device_batch(cfg.batch_size)
        self._viz_counter = 0  # --show_pred PNG fallback numbering
        self._async_copy_ok = True  # cleared on first missing-API probe
        # --precompile: geometries already warmed (or warming) in background
        # (vftlint GUARDED_BY: _precompiled under the 'precompile' lock —
        # the run loop and prior warmup threads race on membership)
        self._precompiled: set = set()
        self._precompile_lock = threading.Lock()
        # --pack_corpus: corpus bucket plan (PackSpec.prepare fills it from
        # the container probes before the packed loop starts)
        self._pack_buckets = None
        # --device_preproc: pad-on-device steps, one memoized jitted step per
        # (sharded?, pad target) — jit caches per INPUT shape, so a single
        # step closing over a mutable target could silently reuse a program
        # traced for a different bucket on a repeat raw geometry
        # (vftlint GUARDED_BY: _frames_steps under the 'flow-steps' lock —
        # precompile warmup threads race the run loop on first-build)
        self._device_preproc = cfg.device_preproc
        self._frames_steps: dict = {}
        self._frames_steps_lock = threading.Lock()
        flow_dtype = jnp.bfloat16 if cfg.flow_dtype == "bfloat16" else jnp.float32
        # D2H transfer dtype: the jitted steps cast their output to this on
        # device; the host upcasts back to fp32. float16 halves the fetched
        # bytes at ≤0.01 px quantization for |flow| ≤ 32 (10 mantissa bits);
        # bfloat16 quarters precision (≤0.16 px at |flow|≈20). float32 is the
        # bit-parity default.
        self._transfer_dtype = {"float32": jnp.float32, "float16": jnp.float16,
                                "bfloat16": jnp.bfloat16}[cfg.transfer_dtype]
        # hoisted out of the reap path: the fetched flow needs a host upcast
        # exactly when a sub-fp32 transfer dtype is configured — decided once
        # here, not re-inspected per batch (fast-tier output-dtype assertion
        # in tests/test_ingest.py covers float16/bfloat16)
        self._upcast = cfg.transfer_dtype != "float32"
        # H2D wire dtype: decoded uint8 end-to-end (the jitted steps' first
        # op is the exact u8→fp32 cast); --float32_wire restores the retired
        # host-side cast at 4× the staged bytes (A/B + bench baseline)
        self._wire = np.float32 if cfg.float32_wire else np.uint8
        if self.feature_type == "raft":
            self.params = self.runner.put_replicated(
                resolve_params(
                    "raft-sintel",
                    convert_torch_fn=convert_raft,
                    init_fn=lambda: raft_init_params(seed=0),
                )
            )
            self._forward = functools.partial(
                raft_forward, corr_impl=cfg.raft_corr, dtype=flow_dtype,
                n_devices=self.runner.num_devices)
            self._forward_frames = functools.partial(
                raft_forward_frames, corr_impl=cfg.raft_corr, dtype=flow_dtype,
                n_devices=self.runner.num_devices)
            self._forward_frames_sharded = functools.partial(
                raft_forward_frames_sharded, mesh=self.runner.mesh,
                corr_impl=cfg.raft_corr, dtype=flow_dtype)
            self._pads_input = True
        elif self.feature_type == "pwc":
            from ..models.pwc import (
                pwc_forward,
                pwc_forward_frames,
                pwc_forward_frames_sharded,
                pwc_init_params,
            )
            from ..weights.convert_torch import convert_pwc

            self.params = self.runner.put_replicated(
                resolve_params(
                    "pwc-sintel",
                    convert_torch_fn=convert_pwc,
                    init_fn=lambda: pwc_init_params(seed=0),
                )
            )
            self._forward = functools.partial(
                pwc_forward, corr_impl=cfg.pwc_corr, dtype=flow_dtype,
                warp_impl=cfg.pwc_warp)
            self._forward_frames = functools.partial(
                pwc_forward_frames, corr_impl=cfg.pwc_corr, dtype=flow_dtype,
                warp_impl=cfg.pwc_warp)
            self._forward_frames_sharded = functools.partial(
                pwc_forward_frames_sharded, mesh=self.runner.mesh,
                corr_impl=cfg.pwc_corr, dtype=flow_dtype,
                warp_impl=cfg.pwc_warp)
            self._pads_input = False
        else:
            raise ValueError(f"not a flow feature type: {self.feature_type}")

    @functools.cached_property
    def _step(self):
        fwd = self._forward
        tdt = self._transfer_dtype

        # pair-split step: (prev, nxt) of equal leading size B shard cleanly
        # along the mesh's data axis at the cost of encoding every interior
        # frame twice. No longer the production multi-device path (the
        # encode-once _frames_step_sharded replaced it) — retained as the
        # parity reference the sharded paths are tested against and for the
        # dryrun/bench harnesses that compare both.
        def step(params, prev, nxt):  # each (B, H, W, 3) float32
            return fwd(params, prev, nxt).astype(tdt)

        return self.runner.jit(step, n_batch_args=2)

    @functools.cached_property
    def _frames_step(self):
        fwd = self._forward_frames
        tdt = self._transfer_dtype

        # single-device meshes skip the pair split: (B+1) frames in, each frame
        # encoded once (the pair-split step encodes interior frames twice —
        # the encoder/pyramid is the flow nets' dominant stage)
        def step(params, frames):  # (B+1, H, W, 3) float32
            return fwd(params, frames).astype(tdt)

        return self.runner.jit(step)

    @functools.cached_property
    def _frames_step_sharded(self):
        fwd = self._forward_frames_sharded
        tdt = self._transfer_dtype

        # multi-device encode-once step: the (B+1)-frame window arrives as its
        # B source frames sharded on the frame axis plus the replicated final
        # frame; each shard's one cross-shard pair is formed on device by halo
        # exchange of the neighbor's boundary feature map
        # (models/{raft,pwc}.*_forward_frames_sharded), so every frame's
        # encoder/pyramid runs exactly once — the pair-split step this
        # replaces encoded every interior frame twice
        def step(params, frames, frame_last):
            # (B, H, W, 3) sharded + (1, H, W, 3) replicated, float32
            return fwd(params, frames, frame_last).astype(tdt)

        return self.runner.jit(step, n_batch_args=1, n_replicated_args=1)

    def _frames_step_for(self, target_hw, sharded: bool):
        """--device_preproc step for one pad target: raw-geometry frames in,
        ``device_pad_to_shape`` to ``target_hw`` as the first fused op (on the
        wire dtype — replicate-pad on uint8 is byte-exact), then the same
        encode-once forward as :attr:`_frames_step` /
        :attr:`_frames_step_sharded`.

        One memoized jitted step PER (sharded?, target): jit caches programs
        by input shape, so a single step closing over a mutable target would
        silently reuse the program traced for a different bucket whenever the
        same raw geometry reappears under a new bucket plan.
        """
        key = (bool(sharded), int(target_hw[0]), int(target_hw[1]))
        with self._frames_steps_lock:
            step = self._frames_steps.get(key)
            if step is None:
                tdt = self._transfer_dtype
                th, tw = key[1], key[2]
                if sharded:
                    fwd = self._forward_frames_sharded

                    def step(params, frames, frame_last):
                        # pad is per-frame (trailing H/W axes), so it shards
                        # trivially along the frame axis
                        return fwd(params,
                                   device_pad_to_shape(frames, (th, tw)),
                                   device_pad_to_shape(frame_last, (th, tw))
                                   ).astype(tdt)

                    step = self.runner.jit(step, n_batch_args=1,
                                           n_replicated_args=1)
                else:
                    fwd = self._forward_frames

                    def step(params, frames):
                        return fwd(params, device_pad_to_shape(
                            frames, (th, tw))).astype(tdt)

                    step = self.runner.jit(step)
                self._frames_steps[key] = step
        return step

    def _host_transform(self, rgb: np.ndarray) -> np.ndarray:
        return pil_edge_resize(rgb, self.cfg.side_size, self.cfg.resize_to_smaller_edge)

    def _device_call(self, frames: np.ndarray, staged: np.ndarray = None,
                     timed: bool = True, pad_target=None):
        """Dispatch one (batch_size+1)-frame window to the jitted step —
        PADDED frames by default; RAW-geometry frames with ``pad_target``
        set (--device_preproc), where the per-target step pads on device.

        Single-device meshes run the shared-frame step whole; multi-device
        meshes shard the B source frames on the frame axis and replicate the
        final frame (encode-once everywhere — no mesh size re-encodes
        interior frames). The --precompile warmup calls this with a zeros
        window of the WIRE dtype so the warmed program is EXACTLY the one
        real dispatch uses.

        ``staged``: the staging-ring buffer backing ``frames``, committed
        against the put results so it is never rewritten while the transfer
        is pending. ``timed=False`` skips the 'transfer' stage attribution —
        the precompile warmup thread must not race the run loop's StageClock.
        """
        put = self._put if timed else self.runner.put
        put_rep = self._put_replicated if timed else self.runner.put_replicated
        if self.runner.num_devices == 1:
            dev = put(np.ascontiguousarray(frames))
            if staged is not None:
                self._staging.commit(staged, dev)
            step = (self._frames_step if pad_target is None
                    else self._frames_step_for(pad_target, sharded=False))
            return step(self.params, dev)
        main = put(np.ascontiguousarray(frames[:-1]))
        last = put_rep(np.ascontiguousarray(frames[-1:]))
        if staged is not None:
            self._staging.commit(staged, (main, last))
        step = (self._frames_step_sharded if pad_target is None
                else self._frames_step_for(pad_target, sharded=True))
        return step(self.params, main, last)

    def _window_geometry(self, h: int, w: int):
        """Padded (TH, TW) a decoded ``h``×``w`` frame dispatches at — the
        shape_bucket (or RAFT /8) arithmetic of :meth:`_dispatch_pairs`,
        shared by the staging-ring window assembly."""
        m = self.cfg.shape_bucket or (8 if self._pads_input else 1)
        return -(-h // m) * m, -(-w // m) * m

    def _window_pad_target(self, h: int, w: int):
        """--device_preproc pad target for a RAW decoded ``h``×``w`` frame:
        the per-video padded geometry, widened to its corpus bucket when a
        packed run's bucket plan is live — the same (TH, TW) the host pad
        would have staged, now applied on device."""
        geom = self._window_geometry(h, w)
        if self._pack_buckets is not None:
            geom = self._pack_buckets.bucket_for(geom)
        return geom

    def _dispatch_window(self, window):
        """Stage one decoded frame window into a reusable staging-ring buffer
        and dispatch it; returns the async handle :meth:`_collect_pairs`
        materializes.

        The production dispatch path: tail repeat and the geometry pad are
        written IN PLACE into the ring buffer at the wire dtype (uint8 unless
        ``--float32_wire``) — no per-batch ``np.stack``/``np.pad``
        allocations. Byte-identical staging to
        ``_dispatch_pairs(np.stack(window))``: replicate-padding each frame
        then repeating the last padded frame equals repeating then padding.
        """
        n_pairs = len(window) - 1
        h, w = window[0].shape[:2]
        if self._device_preproc:
            # raw-pixels wire: the ring buffer keys by the DECODED geometry
            # (no host pad — plain frame copies) and the per-target jitted
            # step replicate-pads on device, byte-exact on the uint8 wire;
            # the host keeps only the pad arithmetic for the final unpad
            th, tw = self._window_pad_target(h, w)
            buf = self._staging.acquire((self.batch_size + 1, h, w, 3),
                                        self._wire)
            for i, frame in enumerate(window):
                buf[i] = frame
            for i in range(len(window), self.batch_size + 1):
                buf[i] = buf[len(window) - 1]  # static shape: repeat the tail
            pads = pad_split(h, w, th, tw)
            if not (self.cfg.shape_bucket or self._pads_input):
                pads = None  # PWC-at-native parity: no unpad slicing
            flow = self._device_call(buf, staged=buf, pad_target=(th, tw))
            self._start_async_copy(flow)
            return flow, n_pairs, pads
        th, tw = self._window_geometry(h, w)
        buf = self._staging.acquire((self.batch_size + 1, th, tw, 3),
                                    self._wire)
        pads = (0, 0, 0, 0)
        for i, frame in enumerate(window):
            pads = pad_to_shape_into(frame, buf[i])
        for i in range(len(window), self.batch_size + 1):
            buf[i] = buf[len(window) - 1]  # static shape: repeat the tail
        if not (self.cfg.shape_bucket or self._pads_input):
            pads = None  # PWC-at-native parity: no unpad slicing
        flow = self._device_call(buf, staged=buf)
        self._start_async_copy(flow)
        return flow, n_pairs, pads

    def _dispatch_pairs(self, frames: np.ndarray):
        """Dispatch one premade pair-window ARRAY to the device; returns an
        async handle. The compatibility seam for callers holding a stacked
        window (tests, bench, the dryrun harness) — the production loops
        stage through :meth:`_dispatch_window` / the packed collate instead.

        The jitted call returns immediately (JAX async dispatch) and
        ``copy_to_host_async`` enqueues the D2H transfer right behind the
        compute, so the fetch rides the DMA engines while the host decodes
        the next window and the device computes the next batch.
        """
        n_pairs = frames.shape[0] - 1
        # static shape: pad the window to batch_size+1 frames by repeating the tail
        if n_pairs < self.batch_size:
            reps = np.repeat(frames[-1:], self.batch_size - n_pairs, axis=0)
            frames = np.concatenate([frames, reps], axis=0)
        # shape_bucket bounds compiled geometries across a mixed-resolution
        # corpus (one program per bucket); RAFT otherwise pads to the /8
        # contract only (reference behavior)
        pads = None
        if self.cfg.shape_bucket:
            frames, pads = pad_to_multiple(frames, self.cfg.shape_bucket)
        elif self._pads_input:
            frames, pads = pad_to_multiple(frames, 8)
        flow = self._device_call(frames)
        self._start_async_copy(flow)
        return flow, n_pairs, pads

    def _start_async_copy(self, flow) -> None:
        """Enqueue the D2H transfer right behind the compute so the fetch
        rides the DMA engines while the host decodes and the device computes
        the next batch — dense flow is the framework's only D2H-heavy output,
        and both the per-video and packed dispatch paths overlap it."""
        if not self._async_copy_ok:
            return
        try:
            flow.copy_to_host_async()
        except Exception as e:  # noqa: BLE001 — fault-barrier: optional-optimization probe (see below)
            # backend lacks async host copy (AttributeError /
            # NotImplementedError / backend-specific UNIMPLEMENTED
            # runtime errors) — probe once, disarm, and say WHICH error
            # disarmed it, so a genuine transfer fault is visible here
            # instead of resurfacing context-free at _wait (the old
            # blanket `pass` hid it; crashing extraction on an optional
            # optimization would be worse)
            self._async_copy_ok = False
            print(f"[flow] async D2H disabled after "
                  f"{type(e).__name__}: {e}; transfers will not "
                  f"overlap compute", flush=True)

    def _collect_pairs(self, handle) -> np.ndarray:
        """Materialize a dispatched window → (n_pairs, 2, H, W) fp32 host flow."""
        flow, n_pairs, pads = handle
        flow = self._wait(flow)
        if self._upcast:  # sub-fp32 transfer_dtype: upcast on host (the
            flow = flow.astype(np.float32)  # decision is hoisted to __init__)
        if pads is not None:
            flow = unpad(flow, pads)
        # NHWC → reference byte layout (B, 2, H, W)
        return flow[:n_pairs].transpose(0, 3, 1, 2)

    def _run_pairs(self, frames: np.ndarray) -> np.ndarray:
        """Flow for all consecutive pairs of (N, H, W, 3) frames (uint8 wire
        dtype or float) → (N-1, 2, H, W)."""
        return self._collect_pairs(self._dispatch_pairs(frames))

    # --- geometry precompile (--precompile) --------------------------------

    def _decoded_geometry(self, width: int, height: int):
        """(H, W) of a decoded frame after ``_host_transform`` — the RAW
        geometry ``--device_preproc`` windows stage and ship at — from the
        container probe's native ``width``×``height``."""
        if self.cfg.side_size is not None:
            w, h = edge_resize_size(width, height, self.cfg.side_size,
                                    self.cfg.resize_to_smaller_edge)
        else:
            w, h = width, height
        return h, w

    def _padded_geometry(self, width: int, height: int):
        """(H, W) of the padded device window a native ``width``×``height``
        video will dispatch: the host edge-resize sizing followed by the
        shape_bucket (or RAFT /8) padding — the same arithmetic
        ``_host_transform`` + ``_dispatch_pairs`` apply per frame."""
        return self._window_geometry(*self._decoded_geometry(width, height))

    def _start_precompile(self, width: int, height: int) -> None:
        """Warm the jitted step for this video's geometry while decode runs.

        Mixed-resolution corpora otherwise pay each new geometry's compile
        (20-100 s over a TPU tunnel) serially at the first dispatch, with the
        mesh idle. The video's decoded geometry is known from the container
        probe before any frame decodes, so a daemon thread runs the step once
        on a zeros window of the padded geometry — jit's signature cache is
        shared across threads, so the real first window either finds the
        program compiled or blocks on the in-flight compile instead of
        starting its own. One wasted zeros execution per NEW geometry; repeat
        geometries return immediately.
        """
        self._start_precompile_padded(
            self._padded_geometry(width, height),
            raw_hw=(self._decoded_geometry(width, height)
                    if self._device_preproc else None))

    def _start_precompile_padded(self, padded_hw, raw_hw=None) -> None:
        """Warm the device program for an already-padded (H, W) geometry —
        the packed loop warms each video's *bucket* geometry (the program the
        packed windows actually dispatch) rather than its own padding.

        ``raw_hw`` (--device_preproc): the decoded geometry real windows
        stage at; the warmed program is then the per-pad-target step over
        raw-geometry input — warming the padded-input program would warm one
        no dispatch ever runs."""
        h, w = padded_hw
        key = (h, w) if raw_hw is None else (h, w) + tuple(raw_hw)
        with self._precompile_lock:
            if key in self._precompiled:
                return
            self._precompiled.add(key)

        def warm():
            try:
                import jax

                # wire dtype (uint8 unless --float32_wire): the warmed
                # program must be the one the real dispatch uses
                if raw_hw is not None:
                    window = np.zeros(
                        (self.batch_size + 1,) + tuple(raw_hw) + (3,),
                        self._wire)
                    handle = self._device_call(window, timed=False,
                                               pad_target=(h, w))
                else:
                    window = np.zeros((self.batch_size + 1, h, w, 3),
                                      self._wire)
                    handle = self._device_call(window, timed=False)
                # host-sync: warmup thread blocks on the zeros window off the critical path by design
                jax.block_until_ready(handle)
            except Exception as e:  # noqa: BLE001 — fault-barrier: best-effort warmup; the real dispatch compiles inline and surfaces any genuine error
                print(f"[flow] geometry precompile ({h}x{w}) failed: "
                      f"{type(e).__name__}: {e}; the first window will "
                      "compile inline", flush=True)

        threading.Thread(target=warm, daemon=True,
                         name=f"flow-precompile:{h}x{w}").start()

    # --- corpus packing (--pack_corpus) ------------------------------------

    def pack_spec(self):
        """Corpus-packing seam for dense flow: a slot is one frame *pair*.

        ``open_clips`` yields ``(2, Hb, Wb, 3)`` uint8 pairs already padded to
        the video's bucket geometry (``ShapeBuckets`` over the corpus's
        container probes — ≤ ``--pack_buckets`` compiled programs for a
        mixed-resolution corpus) — or RAW ``(2, H, W, 3)`` decoded pairs
        under ``--device_preproc``, where queues key per decoded geometry
        and the per-pad-target step replicate-pads on device (byte-exact on
        the uint8 wire; the bucket plan still bounds compiled programs
        because the pad target is bucketed). ``collate`` chains
        stream-consecutive pairs
        back into one ``(batch_size + 1)``-frame shared-frame window — the
        same encode-once program :meth:`_device_call` runs in the per-video
        loop (frame-sharded with halo exchange on multi-device meshes) — so
        the tail of video N's pairs co-batches with the head of video N+1 at
        the cost of one burned frame position per video boundary inside a
        window. Each pair's flow is a pure function of its two frames under
        a fixed program, so packed outputs are byte-identical to the
        per-video loop whenever the bucket equals the video's own padded
        geometry (always true for single-geometry corpora; a merged bucket
        carries --shape_bucket's documented border-perturbation caveat).

        ``--show_pred`` keeps the per-video loop: its frame+flow
        visualizations assume video order.
        """
        if self.cfg.show_pred:
            return None
        from ..parallel.packer import PackSpec, ShapeBuckets

        batch = self.batch_size  # pairs per window

        def prepare(paths):
            from ..io.video import probe_geometries

            geoms = [self._padded_geometry(w, h)
                     for w, h in probe_geometries(paths).values()]
            self._pack_buckets = (
                ShapeBuckets(geoms, self.cfg.pack_buckets) if geoms else None)

        def open_clips(path):
            meta, frames = self._open_video(path)
            geom = self._padded_geometry(meta.width, meta.height)
            bucket = (self._pack_buckets.bucket_for(geom)
                      if self._pack_buckets is not None else geom)
            raw_hw = (self._decoded_geometry(meta.width, meta.height)
                      if self._device_preproc else None)
            if self.cfg.precompile:
                self._start_precompile_padded(bucket, raw_hw=raw_hw)
            info = {
                "fps": meta.fps,
                "timestamps_ms": [],
                # zero-pair videos reproduce the per-video loop's quirk of
                # shaping the empty output from the NATIVE container geometry
                "native_hw": (meta.height, meta.width),
                "pads": (0, 0, 0, 0),
            }
            if raw_hw is not None:
                # raw wire: the step pads on device; the host keeps only the
                # pad arithmetic so finalize can unpad the fetched flow
                info["pads"] = pad_split(raw_hw[0], raw_hw[1], *bucket)

            def clips():
                prev = None
                for rgb, pos in self._timed_frames(frames):
                    info["timestamps_ms"].append(pos)
                    if raw_hw is None:
                        rgb, info["pads"] = pad_to_shape(rgb, bucket)
                    if prev is not None:
                        yield np.stack([prev, rgb])
                    prev = rgb

            return info, clips()

        def collate(clips, stream_keys):
            # chain consecutive pairs (same stream, idx + 1) into a shared-
            # frame window of `batch` pairs / `batch + 1` frame positions; a
            # chain break costs one extra frame position, and the window tail
            # repeats the last frame exactly like the per-video loop's
            # partial-batch padding. Frames are written straight into a
            # staging-ring buffer at the wire dtype (uint8 unless
            # --float32_wire) — no per-batch stack/cast allocation; step()
            # commits the buffer against its device_put below.
            capacity = batch + 1
            buf = self._staging.acquire((capacity,) + clips[0].shape[1:],
                                        self._wire)
            n_frames, n_used, row_of, last = 0, 0, [], None
            for clip, (stream, idx) in zip(clips, stream_keys):
                chained = last == (stream, idx - 1)
                if n_frames + (1 if chained else 2) > capacity:
                    break
                if not chained:
                    buf[n_frames] = clip[0]
                    n_frames += 1
                buf[n_frames] = clip[1]
                n_frames += 1
                row_of.append(n_frames - 2)
                last = (stream, idx)
                n_used += 1
            while n_frames < capacity:
                buf[n_frames] = buf[n_frames - 1]
                n_frames += 1
            return buf, n_used, row_of

        def step(window):
            # --device_preproc windows arrive at RAW decoded geometry (one
            # queue per geometry, so a window never mixes shapes); the pad
            # target is the bucket that geometry maps to — the same pure
            # function of (h, w) open_clips used for info["pads"]
            pad_target = (self._window_pad_target(*window.shape[1:3])
                          if self._device_preproc else None)
            out = self._device_call(window, staged=window,
                                    pad_target=pad_target)
            # same overlap as the per-video loop's _dispatch_window: the
            # packer fetches this batch only when the bucket's NEXT batch
            # dispatches, so the transfer races compute, not the fetch
            self._start_async_copy(out)
            return out

        def finalize(path, rows, info):
            if rows.shape[0] == 0:
                h, w = info["native_hw"]
                flow = np.zeros((0, 2, h, w), np.float32)
            else:
                if self._upcast:  # sub-fp32 transfer_dtype: upcast on host
                    rows = rows.astype(np.float32)  # (hoisted decision)
                if any(info["pads"]):
                    rows = unpad(rows, info["pads"])
                # NHWC rows → reference byte layout (n_pairs, 2, H, W)
                flow = rows.transpose(0, 3, 1, 2)
            return {
                self.feature_type: flow,
                "fps": np.array(info["fps"]),
                "timestamps_ms": np.array(info["timestamps_ms"]),
            }

        return PackSpec(batch_size=batch, empty_row_shape=(0, 0, 2),
                        open_clips=open_clips, step=step, finalize=finalize,
                        collate=collate, prepare=prepare)

    def extract(self, video_path: str) -> Dict[str, np.ndarray]:
        meta, frames_iter = self._open_video(video_path)
        if self.cfg.precompile:
            # geometry known from the container probe: overlap this video's
            # (possibly first-of-its-geometry) compile with its decode
            self._start_precompile(meta.width, meta.height)
        timestamps_ms: List[float] = []
        flow_frames: List[np.ndarray] = []
        window: List[np.ndarray] = []
        # bounded in-flight device windows: deep enough to overlap fetch with
        # compute + decode, bounded so a long video can't pin every batch's
        # full-res flow in HBM
        pending: deque = deque()
        max_pending = max(self.cfg.prefetch_depth, 1)

        self._viz_counter = 0  # per-video PNG numbering

        def collect_one():
            stack, handle = pending.popleft()
            flow = self._collect_pairs(handle)
            flow_frames.extend(flow)
            if self.cfg.show_pred:
                self._show(stack[:-1], flow, video_path)

        def flush():
            if len(window) > 1:
                # ring-staged dispatch at the wire dtype; a frame stack is
                # (re)materialized only for --show_pred's visualizations
                pending.append((np.stack(window) if self.cfg.show_pred
                                else None,
                                self._dispatch_window(window)))
                while len(pending) > max_pending:
                    collect_one()

        for rgb, pos in self._timed_frames(frames_iter):
            timestamps_ms.append(pos)
            window.append(rgb)
            if len(window) - 1 == self.batch_size:
                flush()
                window = [window[-1]]  # carry last frame (reference :143-146)
        flush()  # final partial batch of ≥ 2 frames (reference :147-151)
        while pending:
            collect_one()

        h, w = (flow_frames[0].shape[-2:]) if flow_frames else (meta.height, meta.width)
        return {
            self.feature_type: (
                np.stack(flow_frames) if flow_frames else np.zeros((0, 2, h, w), np.float32)
            ),
            "fps": np.array(meta.fps),
            "timestamps_ms": np.array(timestamps_ms),
        }

    def _show(self, frames: np.ndarray, flows: np.ndarray, video_path: str = "") -> None:
        """Frame + color-wheel flow side by side (``extract_raft.py:165-178``).

        Headless hosts (every TPU pod) have no display for ``cv2.imshow``; the
        visualizations are written as ``<output>/<type>_viz/<stem>_NNNNN.png``
        instead (the ``<stem>_<key>.npy`` naming convention), so ``--show_pred``
        stays useful over ssh. Without OpenCV installed, degrades to a stats line.
        """
        try:
            import cv2
        except ImportError:
            for flow in flows:
                print(f"flow: mean |u|={np.abs(flow[0]).mean():.3f} "
                      f"|v|={np.abs(flow[1]).mean():.3f} (no cv2 for visualization)")
            return

        from ..utils.flow_viz import flow_to_image

        stem = os.path.splitext(os.path.basename(video_path))[0] or "video"
        # cv2.imshow can hard-crash (not raise) without a display server; only
        # attempt it when one is advertised
        has_display = bool(os.environ.get("DISPLAY") or os.environ.get("WAYLAND_DISPLAY"))
        for frame, flow in zip(frames, flows):
            img = flow_to_image(flow.transpose(1, 2, 0))
            stacked = np.concatenate([frame.astype(np.uint8), img], axis=0)
            bgr = cv2.cvtColor(stacked, cv2.COLOR_RGB2BGR)
            if has_display:
                try:
                    cv2.imshow("frame + flow", bgr)
                    cv2.waitKey(1)
                    continue
                except Exception:  # fault-barrier: headless-host probe; falls back to PNG dump
                    has_display = False
            viz_dir = self.output_dir + "_viz"
            os.makedirs(viz_dir, exist_ok=True)
            cv2.imwrite(os.path.join(viz_dir, f"{stem}_{self._viz_counter:05d}.png"), bgr)
            self._viz_counter += 1
