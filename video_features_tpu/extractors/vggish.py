"""VGGish audio extractor: mp4/wav → log-mel examples → 128-d embeddings.

Behavioral spec — ``/root/reference/models/vggish/extract_vggish.py``:
- ``.wav`` inputs consumed directly; ``.mp4`` goes through the two-stage
  ffmpeg extraction (mp4 → aac → wav, ``utils/utils.py:172-201``), with
  ``keep_tmp_files`` controlling cleanup (``:107-110``);
- wav → (N, 96, 64) log-mel examples on the host (vggish_src DSP — ported in
  :mod:`video_features_tpu.audio.melspec`);
- VGG forward → (N, 128) raw embeddings. The reference instantiates the PCA
  postprocessor but never applies it (``:57,104-116``); reproduced via
  ``postprocess=False`` default with the processor available for opt-in;
- output dict: ``{'vggish': (N, 128)}`` (no fps/timestamps — audio model).

TPU design: examples are padded to a static batch so each audio length bucket
compiles once; the forward runs jitted on device. ``--device_preproc`` moves
the log-mel DSP itself on device: the host ships raw (N, 15600) float32 PCM
slabs (``melspec.wav_to_pcm_slabs``) and the jitted step runs the fused
framing → |rfft| → mel matmul → log prologue
(:func:`video_features_tpu.ops.audio.log_mel_examples`, ≤2e-5 vs the numpy
oracle) before the VGG stack. The wire grows 6144→15600 floats per example
(raw PCM is bigger than its mel summary) — the trade is host-CPU relief: the
strided-FFT DSP leaves the decode pool for the accelerator.
"""

from __future__ import annotations

import functools
import os
from typing import Dict

import numpy as np

import jax.numpy as jnp

from ..audio.melspec import wav_to_examples, wav_to_pcm_slabs
from ..io import ffmpeg as ffmpeg_io
from ..ops.audio import log_mel_examples
from ..models.vggish import (
    EMBEDDING_SIZE,
    Postprocessor,
    VGGish,
    convert_tf_vggish,
    vggish_init_params,
)
from ..weights.store import resolve_params
from .base import Extractor, pad_batch

# examples per jitted call; audio shorter than this pads, longer chunks
EXAMPLE_BATCH = 32


class ExtractVGGish(Extractor):
    # --device_preproc: the log-mel DSP runs as a fused jitted prologue
    # (ops/audio.log_mel_examples) over raw PCM slabs; host melspec stays the
    # parity oracle (≤2e-5, tests/test_device_preproc.py)
    supports_device_preproc = True

    def __init__(self, cfg):
        super().__init__(cfg)
        self._device_preproc = cfg.device_preproc
        # examples per device step, rounded to a multiple of the mesh size
        self.example_batch = self.runner.device_batch(EXAMPLE_BATCH)
        self.model = VGGish()
        self.params = self.runner.put_replicated(
            resolve_params(
                "vggish",
                convert_tf_fn=convert_tf_vggish,  # reference ships a TF-slim checkpoint
                init_fn=lambda: vggish_init_params(seed=0),
            )
        )
        # reference parity: processor constructed, applied only on request —
        # --vggish_postprocess (vendored AudioSet params) or an explicit
        # VFT_VGGISH_PCA_PARAMS path (env var implies opt-in, as before)
        pca_path = os.environ.get("VFT_VGGISH_PCA_PARAMS")
        if pca_path is None and self.cfg.vggish_postprocess:
            pca_path = os.path.join(
                os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
                "weights", "data", "vggish_pca_params.npz")
        self.postprocessor = Postprocessor(pca_path) if pca_path else None

    def _forward(self, params, examples):
        # (B, 96, 64) float32; pure per-row — the paged dispatch path wraps
        # this same body (parallel/pages.paged_program)
        return self.model.apply({"params": params}, examples)

    @functools.cached_property
    def _step(self):
        return self.runner.jit(self._forward)

    def _pcm_forward(self, params, pcm):
        # (B, 15600) float32 raw PCM; pure per-row — the log-mel prologue
        # fuses into the VGG stack, and the paged dispatch path wraps this
        # same body (parallel/pages.paged_program)
        return self.model.apply({"params": params}, log_mel_examples(pcm))

    @functools.cached_property
    def _pcm_step(self):
        return self.runner.jit(self._pcm_forward)

    def pack_spec(self):
        """Corpus-packing seam: every device slot is one fixed ``(96, 64)``
        log-mel example, so the whole corpus shares a single shape queue —
        the structurally simplest PackSpec in the repo (audio was excluded
        from PR 4's RGB-only packing for no structural reason). The VGG
        forward has no cross-sample ops and packed batches run the SAME
        jitted program at the same static ``example_batch`` shape, so
        embeddings are byte-identical to the per-video loop; the PCA
        postprocessor (when enabled) runs per video in ``finalize``, exactly
        where the per-video loop applies it."""
        from ..parallel.packer import PackSpec

        def open_clips(path):
            wav_path = path
            aac_path = None
            extracted = False
            if not path.endswith(".wav"):
                wav_path, aac_path = ffmpeg_io.extract_wav_from_mp4(
                    path, self.tmp_dir)
                extracted = True

            # --device_preproc slots are (15600,) raw PCM slabs (the log-mel
            # runs in the step); default slots are (96, 64) host examples —
            # both fixed shapes, so either way one corpus-wide shape queue
            to_rows = (wav_to_pcm_slabs if self._device_preproc
                       else wav_to_examples)

            def clips():
                try:
                    for example in to_rows(wav_path):
                        yield example
                finally:
                    # generator close/exhaustion = the per-video loop's
                    # finally: temp audio never outlives its video's stream
                    if extracted and not self.cfg.keep_tmp_files:
                        for p in (wav_path, aac_path):
                            if p and os.path.exists(p):
                                os.remove(p)

            return {}, clips()

        batch_step = self._pcm_step if self._device_preproc else self._step

        def step(examples):
            # _put: 'transfer'-stage attribution (time + staged bytes); the
            # packer commits the staged ring buffer after the step
            return batch_step(self.params, self._put(examples))

        def finalize(path, rows, info):
            if self.postprocessor is not None:
                rows = self.postprocessor.postprocess(rows)
            return {self.feature_type: rows}

        forward = (self._pcm_forward if self._device_preproc
                   else self._forward)
        return PackSpec(batch_size=self.example_batch,
                        empty_row_shape=(EMBEDDING_SIZE,),
                        open_clips=open_clips, step=step, finalize=finalize,
                        **self._paged_fields(forward, self.params,
                                             self.example_batch))

    def extract(self, video_path: str) -> Dict[str, np.ndarray]:
        wav_path = video_path
        aac_path = None
        extracted = False
        if not video_path.endswith(".wav"):
            wav_path, aac_path = ffmpeg_io.extract_wav_from_mp4(video_path, self.tmp_dir)
            extracted = True
        try:
            if self._device_preproc:  # (N, 15600) raw PCM; log-mel in-step
                examples = wav_to_pcm_slabs(wav_path)
                step = self._pcm_step
            else:
                examples = wav_to_examples(wav_path)  # (N, 96, 64)
                step = self._step
            feats = []
            for i in range(0, len(examples), self.example_batch):
                chunk = examples[i : i + self.example_batch]
                valid = len(chunk)
                batch = self._put(pad_batch(chunk, self.example_batch))
                # stays on device; one host fetch per video
                feats.append(step(self.params, batch)[:valid])
                self._throttle(feats)
            out = (
                self._wait(jnp.concatenate(feats, axis=0))
                if feats
                else np.zeros((0, EMBEDDING_SIZE), np.float32)
            )
            if self.postprocessor is not None:
                out = self.postprocessor.postprocess(out)
            return {self.feature_type: out}
        finally:
            if extracted and not self.cfg.keep_tmp_files:
                for p in (wav_path, aac_path):
                    if p and os.path.exists(p):
                        os.remove(p)
