"""Per-model extraction pipelines sharing one skeleton.

The reference duplicates the decode→transform→batch→forward→collect→output skeleton
in every ``extract_<name>.py`` (SURVEY.md §1); here it is factored once into
:class:`base.Extractor` with per-model subclasses that supply the host transform,
the window plan, and the jitted device step.
"""

def get_extractor(cfg):
    """Instantiate the extractor for ``cfg.feature_type`` (lazy imports keep
    startup light, mirroring the reference's in-branch imports ``main.py:15-33``)."""
    ft = cfg.feature_type
    if ft == "resnet50":
        from .resnet import ExtractResNet50
        return ExtractResNet50(cfg)
    if ft == "r21d_rgb":
        from .r21d import ExtractR21D
        return ExtractR21D(cfg)
    if ft == "i3d":
        from .i3d import ExtractI3D
        return ExtractI3D(cfg)
    if ft in ("raft", "pwc"):
        from .flow import ExtractFlow
        return ExtractFlow(cfg)
    if ft == "vggish":
        from .vggish import ExtractVGGish
        return ExtractVGGish(cfg)
    raise ValueError(f"unknown feature_type: {ft}")
