"""Shared extraction pipeline skeleton.

Factors the loop every reference extractor re-implements (``extract_*.py``): iterate
videos with a per-video fault barrier (log & continue — ``extract_i3d.py:107-117``),
hand each finished feature dict to the output action, track progress. Adds what the
reference lacks: a done-manifest for resume, device-count awareness, and the
reliability layer (:mod:`..reliability`) — classified errors, bounded retry with
backoff for transient failures, a per-video watchdog, a failure manifest, and a
``--max_failures`` circuit breaker.
"""

from __future__ import annotations

import abc
import contextlib
import os
import sys
import threading
import time
from collections import deque
from typing import Dict, List, Optional, Sequence

import jax
import numpy as np

from ..config import ExtractionConfig, resolve_model_defaults
from ..io.filelist import form_video_list
from ..io.output import (
    AsyncOutputWriter,
    WriteHandle,
    feature_output_dir,
    load_done_set,
    write_outputs,
)
from ..io.video import open_video, open_video_segment, plan_segments, probe_video
from ..io import ffmpeg as ffmpeg_io
from ..parallel import MeshRunner
from ..parallel.pipeline import DecodePrefetcher, HostStagingRing
from ..parallel.mesh import enable_compilation_cache
from ..reliability import (
    CircuitBreakerTripped,
    DeviceError,
    RetryPolicy,
    VideoTimeoutError,
    classify,
    failed_manifest_path,
    fault_point,
    prune_failures,
    record_failure,
    retry_call,
    run_with_timeout,
)
from ..obs import MetricsRegistry, SpanJournal
from ..obs.journal import JOURNAL_NAME
from ..utils.metrics import (
    StageClock,
    decode_starvation_warning,
    maybe_profiler,
    metrics_enabled,
)


# Active only while the multi-model serving layer (MultiModelSessions)
# constructs a co-resident model's extractor: the dict names already-built
# resources (mesh runner, host staging ring) the new extractor must REUSE
# instead of building its own — co-resident models share one mesh and one
# staging budget by design. Set/cleared on the constructing (daemon) thread
# only, inside _shared_construction; never touched from worker threads.
_CONSTRUCTION_SHARING: Dict[str, object] = {}


@contextlib.contextmanager
def _shared_construction(**resources):
    """Make ``Extractor.__init__`` reuse ``resources`` for the duration."""
    _CONSTRUCTION_SHARING.update(resources)
    try:
        yield
    finally:
        _CONSTRUCTION_SHARING.clear()


class Extractor(abc.ABC):
    """Base class for all per-model pipelines."""

    # True for models that consume the open_video frame stream (resnet50, flow,
    # i3d); r21d (whole-video torchvision-style decode) and vggish (audio)
    # don't, so the decode pool would prefetch frames nobody reads
    uses_frame_stream = False

    # True for models with a --device_resize path (the host PIL edge resize
    # moves inside the jitted step); others print a notice and keep the
    # bit-parity host resize
    supports_device_resize = False

    # True for models with a --device_preproc path (the remaining host-side
    # preprocess — edge resize, /8 pad, log-mel — runs as a fused jitted
    # prologue and the host ships raw decoded data); models without one
    # print a notice and keep their host preprocess
    supports_device_preproc = False

    def __init__(self, cfg: ExtractionConfig):
        cfg = resolve_model_defaults(cfg)
        cfg.validate()
        self.cfg = cfg
        self.feature_type = cfg.feature_type
        # persistent compilation cache (--compilation_cache): applied before
        # the mesh (and so before any compile) — see docs/performance.md
        if cfg.compilation_cache:
            enable_compilation_cache(cfg.compilation_cache)
        # per-feature-type subdirs, as the reference joins them (extract_i3d.py:77-78)
        self.output_dir = feature_output_dir(cfg.output_path, cfg.feature_type)
        self.tmp_dir = os.path.join(cfg.tmp_path, cfg.feature_type)
        # data-parallel mesh every device step runs on; --num_devices selects the
        # mesh size (None = all local devices), replacing the reference's
        # thread-per-GPU dispatch (/root/reference/main.py:37-47). A model
        # co-loaded by the multi-model serving layer reuses the primary
        # extractor's runner (one mesh for all co-resident models).
        self.runner = (_CONSTRUCTION_SHARING.get("runner")
                       or MeshRunner(cfg.num_devices, cfg.matmul_precision))
        # per-video stage clock; active only when metrics are enabled (run())
        self.clock: Optional[StageClock] = None
        # telemetry (docs/observability.md): the span/event journal
        # (--telemetry_dir) and the metrics registry. Opened by
        # _open_telemetry (run resources); a co-loaded serving model shares
        # the primary's instances — one journal file, one registry, one
        # writer thread across every co-resident model
        self._journal: Optional[SpanJournal] = \
            _CONSTRUCTION_SHARING.get("journal")
        self._metrics: Optional[MetricsRegistry] = \
            _CONSTRUCTION_SHARING.get("metrics")
        self._owns_journal = False
        # cross-video decode pool; created by run() when --decode_workers > 1
        # (0 = auto: _resolve_decode_workers picks the start size and the
        # serving daemon resizes it live); _decode_workers is the resolved
        # pool size the run loops use as their schedule-ahead window
        self._decode_pool: Optional[DecodePrefetcher] = None
        self._decode_workers = max(cfg.decode_workers, 1)
        # reusable host staging buffers (docs/performance.md "ingest fast
        # path"): frame-path device batches are assembled into a small
        # per-geometry ring of preallocated buffers instead of a fresh
        # np.stack allocation per batch; a buffer is never rewritten while
        # its device_put is pending, and blocked-on-transfer time lands on
        # the 'transfer' stage. Depth covers the prefetch pipeline (`depth`
        # transfers in flight + one being consumed + one being filled).
        # (a co-loaded model shares the primary's ring: one staging budget,
        # one commit discipline, across every co-resident model's batches)
        self._staging = (_CONSTRUCTION_SHARING.get("staging")
                         or HostStagingRing(
                             depth=max(cfg.prefetch_depth, 1) + 2,
                             on_wait=self._transfer_wait))
        if cfg.device_resize and not type(self).supports_device_resize:
            print(f"--device_resize ignored: {cfg.feature_type} has no "
                  "device-side resize path (use --device_preproc for the "
                  "every-model device preprocessing surface); keeping the "
                  "host PIL resize")
        if cfg.device_preproc and not type(self).supports_device_preproc:
            print(f"--device_preproc ignored: {cfg.feature_type} has no "
                  "device-side preprocessing path; keeping the host "
                  "preprocess")
        # async output writer; created by run() for save_numpy jobs unless
        # --sync_writer opted out. _pending_writes holds (path, WriteHandle)
        # for extractions whose output is still on the writer thread — on
        # self (not loop-local) so an interrupted run can still account the
        # writes the writer drains during shutdown
        self._writer: Optional[AsyncOutputWriter] = None
        self._pending_writes: deque = deque()
        # videos that succeeded in the current run() (failure-manifest pruning)
        self._succeeded: List[str] = []
        # per-run accounting shared by the per-video and packed loops
        self._ok = 0
        self._failures = 0
        # --pack_corpus occupancy of the last packed run (bench/run.py report):
        # {"real_slots", "dispatched_slots", "occupancy", "video_clips"}
        self._pack_stats: Optional[Dict] = None
        # content-addressed feature cache (--cache_dir, docs/caching.md):
        # the config+weights fingerprint is hashed ONCE here; per-video keys
        # combine it with each container's streaming content digest.
        # _cache_keys remembers consult-time keys until publish (or terminal
        # failure) so the miss → extract → publish path never re-hashes.
        self._cache = None
        self._cache_fp: Optional[str] = None
        self._cache_keys: Dict[str, str] = {}
        if cfg.cache_dir:
            from ..cache import FeatureCache, fingerprint_digest

            try:
                self._cache_fp = fingerprint_digest(cfg)
                # a co-loaded serving model reuses the primary's store (one
                # LRU clock over the shared dir, and no redundant restart
                # rescan on the daemon thread); the fingerprint above stays
                # per model, so entries never collide. Key PRESENT with None
                # inherits the primary's disabled state (its store failed to
                # open — two independent stores over one dir would be worse)
                if "cache" in _CONSTRUCTION_SHARING:
                    self._cache = _CONSTRUCTION_SHARING["cache"]
                else:
                    self._cache = FeatureCache(cfg.cache_dir,
                                               cfg.cache_max_bytes)
            except OSError as e:
                # an unreadable checkpoint / cache dir disables the cache for
                # this run (pass-through), it must not block extraction
                print(f"warning: --cache_dir disabled: {e}", file=sys.stderr)
                self._cache = None
                self._cache_fp = None

    # --- per-model API ---

    @abc.abstractmethod
    def extract(self, video_path: str) -> Dict[str, np.ndarray]:
        """Extract features for one video; keys become output-file suffixes."""

    def _host_transform(self, rgb: np.ndarray) -> np.ndarray:
        """Per-frame host transform applied during decode (override per model)."""
        return rgb

    def pack_spec(self):
        """Corpus-packing seam (``--pack_corpus``): a
        :class:`..parallel.packer.PackSpec` wiring this model's fixed-shape
        clip stream, jitted device step, and output assembly into the
        cross-video packer — or None when the config has no packing path.
        Every extractor packs: RGB paths (resnet50, r21d_rgb, i3d) use
        stacked clip slots, the flow extractors pack frame-pair slots through
        the collate seam into shared-frame windows, and vggish packs fixed
        log-mel slabs. The remaining per-video fallbacks are ``--show_pred``
        debug runs (per-batch prints assume video order) and the single-clip
        frame-sharded flow sandwich (one clip already fills the mesh)."""
        return None

    def _paged_fields(self, forward, params, batch_size: int) -> dict:
        """PackSpec kwargs switching this model's buckets to ragged paged
        dispatch (:mod:`..parallel.pages`, ``--paged_batching``).

        ``forward(params, page)`` is the model's pure per-row device step
        (preprocess + apply, NOT jitted — this helper compiles the paged
        wrapper once via :meth:`..parallel.mesh.MeshRunner.jit_paged`, which
        donates the row-table buffer). ``batch_size`` is the model's bucketed
        batch budget; the page holds ``ceil(batch_size / pages_in_flight)``
        rows so total in-flight rows match one bucketed batch. Returns ``{}``
        when ``--no_paged_batching`` globally opts out — callers splat the
        result into their PackSpec; models that must stay bucketed
        (geometry-variable wire formats, collate dispatch) simply never call
        this, which is the per-model opt-out the spec documents."""
        if not self.cfg.paged_batching:
            return {}
        from ..parallel.pages import page_rows_for, paged_program

        depth = self.cfg.pages_in_flight
        page_rows = page_rows_for(batch_size, depth, self.runner.device_batch)
        # memoized per (forward, page budget): pack_spec() runs once per
        # run()/retry pass, and a fresh jax.jit instance would recompile the
        # whole paged program each time (forwards are bound methods, so key
        # by the underlying function — stable across pack_spec calls)
        key = (getattr(forward, "__func__", forward), page_rows, depth)
        cache = self.__dict__.setdefault("_paged_programs", {})
        jitted = cache.get(key)
        if jitted is None:
            jitted = self.runner.jit_paged(paged_program(forward))
            cache[key] = jitted

        def paged_step(page, table):
            # the table's device value is DONATED into the jitted call; the
            # packer holds the host staging buffers until `out` resolves
            return jitted(params, self._put(page), self._put(table))

        return {"paged_step": paged_step, "page_rows": page_rows,
                "pages_in_flight": depth}

    # --- decode (frame-stream models route through the prefetcher) ---

    def _open_inline(self, video_path: str):
        return open_video(
            video_path,
            extraction_fps=self.cfg.extraction_fps,
            tmp_path=self.tmp_dir,
            keep_tmp_files=self.cfg.keep_tmp_files,
            use_ffmpeg=self.cfg.use_ffmpeg,
            transform=self._host_transform,
            retries=self.cfg.retries,
            retry_backoff=self.cfg.retry_backoff,
        )

    def _open_video(self, video_path: str):
        """(meta, frames_iter) — prefetched by a decode worker when the pool
        is active (``--decode_workers``), else decoded inline."""
        if self._decode_pool is not None:
            return self._decode_pool.get(video_path)
        return self._open_inline(video_path)

    # auto-segmentation thresholds (--decode_segments 0): a video is worth
    # splitting only when its decode time plausibly dominates a pool slot —
    # proxied by source length — and each resulting segment amortizes its
    # seek + thread cost over a meaningful run of frames
    AUTO_MIN_SOURCE_FRAMES = 256
    AUTO_MIN_SEGMENT_FRAMES = 96

    def _plan_inline(self, video_path: str, max_segments: int):
        """Segment planner handed to the decode pool (``set_segmenter``).

        Returns None (decode sequentially) unless segmentation is both
        enabled and worthwhile. Never raises: a probe failure here falls
        back to the sequential open, which classifies the container with
        full per-video fault attribution.
        """
        cfg = self.cfg
        if cfg.decode_segments == 1 or max_segments < 2:
            return None
        if (cfg.extraction_fps is not None and cfg.use_ffmpeg != "never"
                and ffmpeg_io.have_ffmpeg()):
            # the ffmpeg re-encode resample path decodes a different
            # (re-encoded) container — its parity anchor is the sequential
            # re-encode, so it is never segmented
            return None
        try:
            meta = probe_video(video_path)
        except Exception:  # noqa: BLE001 — fault-barrier: the real open classifies
            return None
        if cfg.decode_segments:
            limit = min(cfg.decode_segments, max_segments)
            min_frames = 2
        else:
            if meta.frame_count < self.AUTO_MIN_SOURCE_FRAMES:
                return None
            limit = max_segments
            min_frames = self.AUTO_MIN_SEGMENT_FRAMES
        return plan_segments(meta, limit, extraction_fps=cfg.extraction_fps,
                             min_segment_frames=min_frames)

    def _open_segment_inline(self, plan, index: int):
        """Decode one planned segment with this model's host transform."""
        return open_video_segment(plan, index, transform=self._host_transform,
                                  seek=self.cfg.segment_seek)

    # --- observability hooks (no-ops unless metrics are enabled) ---

    def _open_telemetry(self) -> None:
        """Open the span journal (``--telemetry_dir``) and the metrics
        registry. Part of the run resources — the batch loops get it per
        ``run()``, the serving daemon for its lifetime. Idempotent; a
        registry set externally (the daemon's) or a journal inherited from
        the construction-sharing seam (a co-loaded model) is kept."""
        if self._metrics is None and (self.cfg.telemetry_dir or self.cfg.serve):
            self._metrics = MetricsRegistry()
        if self.cfg.telemetry_dir and (
                self._journal is None or self._journal.closed):
            self._journal = SpanJournal(
                os.path.join(self.cfg.telemetry_dir, JOURNAL_NAME))
            self._owns_journal = True
        if self._cache is not None:
            # the store reports quarantines/evictions into the same journal
            self._cache.journal = self._journal

    def _emit(self, event: str, **fields) -> None:
        """Append one journal event (no-op without --telemetry_dir); the
        emit is a non-blocking queue put — never the hot path's problem."""
        if self._journal is not None:
            self._journal.emit(event, model=self.feature_type, **fields)

    def _span(self, name: str, **fields):
        """Journal span context (``<name>_start``/``<name>_end`` pair)."""
        if self._journal is None:
            return contextlib.nullcontext()
        return self._journal.span(name, model=self.feature_type, **fields)

    def _mark_succeeded(self, path: str) -> None:
        """Shared per-video success accounting: the run counter, the
        failure-manifest prune list, and telemetry — every success arm
        (inline write, async-write reap, packed finalize, cache-hit replay)
        lands here so the journal's ``video_done`` stream and the
        ``videos_ok_total`` counter agree with the manifests exactly."""
        self._ok += 1
        self._succeeded.append(path)
        self._emit("video_done", video=path)
        if self._metrics is not None:
            self._metrics.inc("videos_ok_total", model=self.feature_type)

    def _timed_frames(self, frames_iter):
        """Attribute host time blocked on decode/transform to the 'decode'
        stage, and account decoded payload bytes (the ingest-throughput
        counter the stage report derives decode MB/s from)."""
        if self.clock is None:
            return frames_iter
        return self.clock.timed_iter(frames_iter, "decode",
                                     bytes_of=lambda item: item[0].nbytes)

    def _wait(self, device_out) -> np.ndarray:
        """Gather a device result, attributing blocked time to 'device_wait'."""
        if self.clock is None:
            return np.asarray(device_out)
        with self.clock.stage("device_wait"):
            return np.asarray(device_out)

    def _transfer_wait(self, seconds: float) -> None:
        """Staging-ring backpressure (blocked until a pending host→device
        copy finished) is transfer time — attribute it to that stage."""
        if self.clock is not None:
            self.clock.add_seconds("transfer", seconds)

    def _put(self, arr):
        """Transfer a host batch onto the mesh (sharded along axis 0),
        attributing host dispatch time and the staged payload bytes to the
        'transfer' stage — the host→device MB/s counter the run report and
        the serve stats op derive from."""
        if self.clock is None:
            return self.runner.put(arr)
        with self.clock.stage("transfer"):
            dev = self.runner.put(arr)
        self.clock.add_bytes("transfer", int(arr.nbytes))
        return dev

    def _put_replicated(self, arr):
        """Replicated transfer with the same 'transfer' attribution. Bytes
        count the HOST payload once (the replication fan-out across devices
        rides the interconnect, not the host staging path)."""
        if self.clock is None:
            return self.runner.put_replicated(arr)
        with self.clock.stage("transfer"):
            dev = self.runner.put_replicated(arr)
        self.clock.add_bytes("transfer", int(arr.nbytes))
        return dev

    def _stage_rows(self, rows: Sequence[np.ndarray],
                    batch_size: Optional[int] = None) -> np.ndarray:
        """Stack equal-shape host rows into a reusable staging-ring buffer
        (zero-padded to ``batch_size``) instead of a fresh ``np.stack`` +
        ``pad_batch`` allocation per batch. The caller must route the staged
        buffer's device value back through ``self._staging.commit`` (the
        prefetcher's ``commit`` hook does this) so the buffer is not
        rewritten while its transfer is pending."""
        return self._staging.stage(rows, batch_size)

    def _throttle(self, outputs: Sequence) -> None:
        """Bound in-flight device work when per-batch results stay on device.

        Deferring the host fetch to one per video removes the implicit
        backpressure the old per-batch ``np.asarray`` provided; without a bound
        the host dispatches every batch of a long video ahead of compute and
        pins them all in HBM. Blocking on the (prefetch_depth+1)-oldest output
        keeps at most ~prefetch_depth batches outstanding.
        """
        depth = max(self.cfg.prefetch_depth, 1)
        if len(outputs) > depth:
            jax.block_until_ready(outputs[-depth - 1])

    # --- shared driver ---

    def video_list(self) -> List[str]:
        return form_video_list(self.cfg.video_paths, self.cfg.file_with_video_paths)

    def run(self, video_paths: Optional[Sequence[str]] = None, progress=None) -> int:
        """Process all videos with the per-video fault barrier; returns #succeeded.

        ``progress``: optional callable invoked after each video (done, total).
        Terminal failures are classified (:func:`..reliability.classify`),
        recorded in the failure manifest, and survived — unless they exceed
        ``--max_failures``, which raises :class:`CircuitBreakerTripped`.
        """
        paths = list(video_paths) if video_paths is not None else self.video_list()
        done = load_done_set(self.output_dir) if self.cfg.resume else set()
        with_metrics = metrics_enabled(self.cfg.profile_dir)
        pack = None
        if self.cfg.pack_corpus:
            pack = self.pack_spec()
            if pack is None:
                print(f"--pack_corpus ignored: {self.feature_type} has no "
                      "packing path under this config (--show_pred debug "
                      "runs and the single-clip frame-sharded flow sandwich "
                      "use the per-video loop)")
        self._open_run_resources()
        try:
            if pack is not None:
                return self._run_packed(pack, paths, done, with_metrics, progress)
            return self._run_loop(paths, done, with_metrics, progress)
        finally:
            self._close_run_resources()

    def _resolve_decode_workers(self) -> int:
        """``--decode_workers 0`` = auto (ROADMAP item 4, first step).

        Starts from a modest CPU-derived pool; the serving daemon then grows
        or shrinks it live from the measured occupancy / decode-MB/s signal
        (:mod:`..serve.autoscale`). Batch runs keep the initial value — they
        have no between-request boundary to resize at.
        """
        workers = self.cfg.decode_workers
        if workers == 0:
            workers = min(4, max(2, (os.cpu_count() or 2) // 2))
            print(f"--decode_workers 0 (auto): starting the decode pool at "
                  f"{workers} worker(s)")
        return workers

    def _open_run_resources(self) -> None:
        """Decode pool + async writer + telemetry + per-run accounting,
        shared by :meth:`run` and the serving daemon's caller-managed
        session."""
        self._open_telemetry()
        workers = self._resolve_decode_workers()
        self._decode_workers = workers
        if workers > 1 and self.uses_frame_stream:
            self._decode_pool = DecodePrefetcher(self._open_inline, workers,
                                                 journal=self._journal)
            self._decode_pool.set_segmenter(self._plan_inline,
                                            self._open_segment_inline)
        elif workers > 1:
            print(f"--decode_workers ignored: {self.feature_type} does not "
                  "consume the frame stream (whole-video / audio decode)")
        if self.cfg.async_writer and self.cfg.on_extraction == "save_numpy":
            # bounded single-writer thread: .npy serialization overlaps the
            # next video's compute; write failures retry like any other
            # transient OutputError, then surface at the per-video reap.
            # depth 2 + the loop's reap-to-one discipline (_run_loop
            # reap_writes(1)) guarantee submit() never blocks inside a
            # video's watchdog window on a predecessor's slow write.
            self._writer = AsyncOutputWriter(
                depth=2,
                retry=RetryPolicy(attempts=self.cfg.retries + 1,
                                  base_delay=self.cfg.retry_backoff))
        self._succeeded = []  # pruned from the failure manifest at exit
        self._ok = 0
        self._failures = 0

    def _close_run_resources(self) -> None:
        """Unwind-safe teardown (run()'s ``finally`` and the daemon's)."""
        # KeyboardInterrupt / a raising progress callback must not leak
        # decode workers busy-waiting on full queues — shut the pool down
        # FIRST so a raising manifest prune can't skip it
        if self._decode_pool is not None:
            self._decode_pool.shutdown()
            self._decode_pool = None
        # drain the writer even on interrupt/breaker: queued jobs finish
        # their atomic writes + done records (write-before-done holds),
        # then account the drained handles so videos that DID complete
        # reach _succeeded (their stale failure records must be pruned —
        # a --retry_failed pass interrupted after its last extract would
        # otherwise leave a video in both manifests forever)
        if self._writer is not None:
            self._writer.close(wait=True)
            self._writer = None
            self._reap_abandoned_writes()
        # even on KeyboardInterrupt / circuit breaker: converge the failure
        # manifest for everything that DID succeed this run
        self._prune_succeeded(self._succeeded)
        # the journal closes LAST so every unwind arm above could still emit;
        # the closed object is kept for the run report's counters (a second
        # run() reopens in append mode). Shared journals (a co-loaded serving
        # model) are closed by their owning primary only.
        if self._owns_journal and self._journal is not None:
            self._journal.close()

    def _process_one(self, path: str,
                     cancelled: Optional[threading.Event] = None,
                     ) -> Optional[WriteHandle]:
        """One attempt at one video: extract → output action → mark done.

        With the async writer active the action + done record are SUBMITTED
        (not performed): the returned :class:`WriteHandle` resolves on the
        writer thread while the loop moves to the next video, and the run
        loop's reap attributes any write failure back to this video. Inline
        mode returns None after writing synchronously.

        ``cancelled`` is set by the watchdog on timeout: an abandoned attempt
        that later wakes up (typically over a partial frame stream — releasing
        the decode-pool slot turns the remaining frames into a clean-looking
        EOF) must discard its results, not write truncated features and a
        done-manifest record for a video the run already counted as failed.
        The check sits BEFORE the submit, so watchdog-cancelled attempts
        never enqueue writes — and the submitted job carries the event, so a
        cancellation landing after this check is still discarded by the
        writer before the done record.
        """

        def check_cancelled(stage: str) -> None:
            if cancelled is not None and cancelled.is_set():
                raise VideoTimeoutError(
                    f"{path}: attempt was cancelled by the watchdog; {stage}")

        fault_point("extract", path)
        feats_dict = self.extract(path)
        check_cancelled("discarding possibly-partial features")
        return self._submit_outputs(path, feats_dict, cancelled=cancelled)

    def _submit_outputs(self, path: str, feats_dict: Dict[str, np.ndarray],
                        cancelled: Optional[threading.Event] = None,
                        from_cache: bool = False) -> Optional[WriteHandle]:
        """One video's output action — shared by the per-video loop's
        :meth:`_process_one`, the packed loop's finalize, and the cache-hit
        replay (``from_cache=True`` skips the republish). A freshly-extracted
        video whose key was consulted this run publishes to the cache HERE,
        before the (possibly async) write — by the time the write resolves,
        concurrent duplicates already hit."""
        if (self._cache is not None and not from_cache
                and (cancelled is None or not cancelled.is_set())):
            key = self._cache_keys.pop(os.path.abspath(path), None)
            if key is not None:
                self._cache.put(key, feats_dict)  # best-effort, never raises
        if self._writer is not None:
            # the job carries the cancel event: a timeout landing between
            # the caller's check and the writer thread picking the job up (or
            # mid-write) still discards before the done record. This put
            # cannot block on a full queue — the run loop reaps down to one
            # outstanding write before starting the next attempt — so a
            # PREDECESSOR's slow write stalls the loop in reap_writes
            # (outside any watchdog), never this video's timeout budget.
            return self._writer.submit(feats_dict, path, self.output_dir,
                                       self.cfg.on_extraction,
                                       cancelled=cancelled)
        # inline mode: the same shared write contract, on this thread
        write_outputs(feats_dict, path, self.output_dir,
                      self.cfg.on_extraction, cancelled=cancelled)
        return None

    # --- feature cache (--cache_dir, docs/caching.md) -------------------------

    def _cache_key_for(self, path: str) -> Optional[str]:
        """Compute (and remember) the cache key for ``path``; None when the
        cache is off or the container cannot be hashed — hashing failures are
        plain misses here, the extraction attempt owns classifying them."""
        if self._cache is None:
            return None
        ap = os.path.abspath(path)
        key = self._cache_keys.get(ap)
        if key is not None:
            return key
        from ..cache import cache_key, file_digest

        try:
            key = cache_key(file_digest(path), self._cache_fp)
        except OSError as e:
            print(f"warning: cache skipped for {path} (cannot hash): {e}",
                  file=sys.stderr)
            return None
        self._cache_keys[ap] = key
        return key

    def _cache_fetch(self, path: str) -> Optional[Dict[str, np.ndarray]]:
        """The cached feature dict for ``path``, or None (miss/disabled).
        Never raises: both loops call it BEFORE their fault barrier. Hash +
        lookup time lands on the 'cache' stage of the report."""
        if self._cache is None:
            return None
        if self.clock is not None:
            with self.clock.stage("cache"):
                key = self._cache_key_for(path)
                feats = self._cache.get(key) if key is not None else None
        else:
            key = self._cache_key_for(path)
            feats = self._cache.get(key) if key is not None else None
        if feats is not None:
            # the key's job is done; a hit republishes nothing
            self._cache_keys.pop(os.path.abspath(path), None)
            self._emit("cache_hit", video=path)
        return feats

    def _publish_cache_hit(self, path: str, feats: Dict[str, np.ndarray],
                           on_done=None) -> None:
        """Serve a hit through the SHARED output path: same atomic writes,
        same done-manifest record (pinned — ``--resume`` must compose), same
        pending-write accounting; zero decode, zero device steps. The caller
        owns the fault barrier (a failed write fails this video like any
        other write failure)."""
        handle = self._submit_outputs(path, feats, from_cache=True)
        if handle is not None:
            self._pending_writes.append((path, handle))
        else:
            self._mark_succeeded(path)
            if on_done is not None:
                on_done(path)

    def _attempt_with_retries(self, path: str) -> Optional[WriteHandle]:
        """Run one video under the watchdog + transient-retry policy.

        Each attempt is watchdog-bounded individually (``--video_timeout``
        limits an *attempt*, not the retry budget). Between attempts the
        decode-pool slot is released so the retry decodes fresh — the stale
        prefetched stream may itself be the failure. Returns the async
        writer's handle for this video's pending output (None in inline
        mode).
        """

        def on_retry(exc, attempt, delay):
            err_class, _ = classify(exc)
            print(f"[{err_class}] attempt {attempt} failed for {path}: {exc}; "
                  f"retrying in {delay:.2g}s")
            if self._decode_pool is not None:
                self._decode_pool.release(path)

        def attempt_once():
            cancel = threading.Event()
            return run_with_timeout(
                lambda: self._process_one(path, cancel),
                self.cfg.video_timeout, path, on_timeout=cancel.set,
            )

        return retry_call(
            attempt_once,
            RetryPolicy(attempts=self.cfg.retries + 1,
                        base_delay=self.cfg.retry_backoff),
            on_retry=on_retry,
        )

    def _reap_abandoned_writes(self) -> None:
        """Account writes the closed writer drained after the loop stopped.

        Runs in ``run()``'s ``finally`` with the writer already closed, so
        every handle has resolved: successes join ``_succeeded`` (their
        stale failure records get pruned), failures are best-effort recorded
        — never raised (this is an unwind path; the in-flight exception, if
        any, must win) and never circuit-breaker counted.
        """
        while self._pending_writes:
            wpath, handle = self._pending_writes.popleft()
            try:
                handle.wait()
            except Exception as e:  # noqa: BLE001 — fault-barrier: unwind-path write accounting; must not mask the in-flight exception
                try:
                    record_failure(self.output_dir, wpath,
                                   e, getattr(e, "attempts", 1))
                except OSError as rec_err:
                    print(f"warning: could not record failure for {wpath}: "
                          f"{rec_err}", file=sys.stderr)
                continue
            self._succeeded.append(wpath)

    def _prune_succeeded(self, succeeded: List[str]) -> None:
        """Drop stale failure records for videos that just succeeded.

        One batched rewrite (not one per success — a mostly-successful retry
        pass over F failures would otherwise cost O(F²) manifest I/O), in the
        run's ``finally`` so KeyboardInterrupt and the circuit breaker still
        converge the manifest. Single-host only: the read-modify-replace
        rewrite would race other hosts' ``record_failure`` appends; on
        multi-host runs stale records simply remain until a single-host
        ``--retry_failed`` pass clears them.
        """
        if not succeeded or jax.process_count() > 1:
            return
        if not os.path.exists(failed_manifest_path(self.output_dir)):
            return
        try:
            prune_failures(self.output_dir, succeeded)
        except (OSError, ValueError) as e:
            # ValueError covers UnicodeDecodeError from a byte-corrupted
            # manifest; raised from run()'s finally it would mask the
            # in-flight exception, so warn instead
            print(f"warning: could not prune {len(succeeded)} failure "
                  f"record(s): {e}", file=sys.stderr)

    def _fail(self, path: str, e: BaseException) -> None:
        """Per-video failure accounting — both run loops' barriers and the
        write reap share it so a write failure is recorded exactly like a
        compute one (classified, manifested, circuit-breaker counted)."""
        self._failures += 1
        # drop the consult-time cache key (nothing will publish it; the
        # daemon's requeue path, which WILL retry, claims the failure before
        # reaching here and keeps the key so retries skip the re-hash)
        self._cache_keys.pop(os.path.abspath(path), None)
        err_class, transient = classify(e)
        attempts = getattr(e, "attempts", 1)
        self._emit("video_failed", video=path, error_class=err_class,
                   transient=transient, attempts=attempts)
        if self._metrics is not None:
            self._metrics.inc("videos_failed_total", model=self.feature_type,
                              error_class=err_class)
        # best-effort: the manifest write hitting the same dying
        # disk as the failure itself must not escape the barrier
        try:
            record = record_failure(self.output_dir, path, e, attempts)
            digest = record["traceback_digest"]
        except OSError as rec_err:
            digest = "unrecorded"
            print(f"warning: could not record failure for {path}: "
                  f"{rec_err}", file=sys.stderr)
        print(e)
        print(f"Extraction failed at: {path} with error (↑). "
              f"Continuing extraction "
              f"[{err_class}, transient={transient}, "
              f"attempts={attempts}, digest={digest}]")
        if (self.cfg.max_failures is not None
                and self._failures > self.cfg.max_failures):
            raise CircuitBreakerTripped(
                f"{self._failures} videos failed (> --max_failures "
                f"{self.cfg.max_failures}); aborting — a failure "
                "rate this high usually has a systemic cause. "
                "Failures so far are recorded in the failure "
                "manifest; fix the cause and rerun with "
                "--retry_failed."
            ) from e

    def _reap_writes(self, limit: int, on_done=None, on_failed=None) -> None:
        """Resolve oldest pending writes until ≤ ``limit`` remain.

        Peek-then-pop: a KeyboardInterrupt inside ``handle.wait()``
        (Event.wait is signal-interruptible) must leave the tuple in the
        deque so the shutdown drain (:meth:`_reap_abandoned_writes`) can
        still account the write — a popped-then-lost handle would strand
        its video's stale failure record forever.

        ``on_done(path)`` / ``on_failed(path, exc)``: the serving daemon's
        per-request bookkeeping hooks. A truthy ``on_failed`` return claims
        the failure (the daemon re-enqueued the video); the shared terminal
        accounting then does not run.
        """
        pending_writes = self._pending_writes
        while len(pending_writes) > limit:
            wpath, handle = pending_writes[0]
            try:
                handle.wait()
            except KeyboardInterrupt:
                raise
            except Exception as e:  # noqa: BLE001 — fault-barrier: the write-side arm of the per-video isolation point
                pending_writes.popleft()
                if on_failed is not None and on_failed(wpath, e):
                    continue
                self._fail(wpath, e)
                continue
            pending_writes.popleft()
            self._mark_succeeded(wpath)
            if on_done is not None:
                on_done(wpath)

    def _run_loop(self, paths, done, with_metrics, progress) -> int:
        todo = [p for p in paths if os.path.abspath(p) not in done]
        workers = self._decode_workers
        extracted = 0  # excludes resume-skipped videos (throughput honesty)
        resumed = 0  # tracked directly: ok - extracted no longer equals it
        # when an async write fails (extracted counts the successful extract,
        # self._ok only counts writes that resolved)
        cursor = 0  # decode-window cursor over `todo`
        # async-writer mode: a video counts `ok` only once its write
        # resolved, so the done/failure manifests and the return value agree
        # with the synchronous path exactly; the deque lives on self so
        # run()'s finally can account handles an interrupt abandoned
        pending_writes = self._pending_writes
        pending_writes.clear()
        t_run = time.perf_counter()

        with maybe_profiler(self.cfg.profile_dir):
            for n, path in enumerate(paths, start=1):
                if os.path.abspath(path) in done:
                    self._ok += 1
                    resumed += 1
                    if progress:
                        progress(n, len(paths))
                    continue
                self.clock = (StageClock(registry=self._metrics,
                                         labels={"model": self.feature_type})
                              if with_metrics else None)
                t0 = time.perf_counter()
                # consult the cache BEFORE decode: a hit dispatches nothing —
                # no decode stream, no device step (_cache_fetch never raises;
                # a hit's WRITE failure still lands on the barrier below)
                feats = self._cache_fetch(path)
                if self._decode_pool is not None:
                    if feats is None:
                        # keep `workers` videos decoding ahead of the consumer
                        for p in todo[cursor : cursor + workers]:
                            self._decode_pool.schedule(p)
                    else:
                        # an earlier miss's window may have prefetch-scheduled
                        # this path — cancel it, nothing will consume it
                        self._decode_pool.release(path)
                    cursor += 1
                try:
                    if feats is not None:
                        self._publish_cache_hit(path, feats)
                        handle = None  # accounted inside the helper
                    else:
                        with self._span("extract", video=path):
                            handle = self._attempt_with_retries(path)
                        extracted += 1
                    if self.clock is not None:
                        print(self.clock.report(path, time.perf_counter() - t0))
                    if handle is not None:
                        pending_writes.append((path, handle))
                    elif feats is None:
                        self._mark_succeeded(path)
                except KeyboardInterrupt:
                    raise
                except Exception as e:  # noqa: BLE001 — fault-barrier: the per-video isolation point
                    self._fail(path, e)
                finally:
                    self.clock = None
                    if self._decode_pool is not None:
                        # cancel this video's decode stream whether it was fully
                        # drained or abandoned by a compute error — an orphaned
                        # worker would pin a permit + max_buffered frames forever
                        self._decode_pool.release(path)
                # bound in-flight writes: the current video's serialization
                # overlaps the NEXT video's decode/compute, older writes must
                # resolve (and be accounted) first. OUTSIDE the barrier: a
                # CircuitBreakerTripped from the reap must abort the run, not
                # be swallowed as video `path`'s failure.
                self._reap_writes(1)
                if progress:
                    progress(n, len(paths))
            self._reap_writes(0)  # tail videos' writes resolve before run() returns
        if with_metrics and (extracted or
                             (self._cache is not None and self._cache.hits)):
            dt = time.perf_counter() - t_run
            hits = f", {self._cache.hits} cache hit(s)" if self._cache else ""
            print(f"extracted {extracted}/{len(paths)} videos "
                  f"({resumed} resumed{hits}) in {dt:.2f}s "
                  f"({extracted / dt:.3f} videos/sec)")
        return self._ok

    def _run_packed(self, spec, paths, done, with_metrics, progress) -> int:
        """Corpus-level continuous batching (``--pack_corpus``).

        Every fixed-shape device batch is filled with clips from however many
        videos are ready (the packer holds partial shape queues ACROSS video
        boundaries — tail of video N packs with head of video N+1) and per-
        clip results scatter back to per-video assemblies that flush through
        the shared output path as each video's last clip lands. The per-video
        invariants of :meth:`_run_loop` are preserved: a poisoned clip stream
        fails only its contributing video (slot-level attribution), transient
        failures retry with a fresh decode, resume/done/failure manifests and
        the circuit breaker behave identically, and per-slot features are
        byte-identical to the unpacked loop (each slot's row is a pure
        function of its clip — no cross-sample ops in the packed steps).

        ``--video_timeout`` here bounds a video's *clip stream* cooperatively
        (checked between clips): with the decode pool active a wedged decode
        thread still trips it, but a hard-wedged inline decode needs the
        per-video loop's thread-cancelling watchdog.
        """
        todo = [p for p in paths if os.path.abspath(p) not in done]
        workers = self._decode_workers
        extracted = 0
        resumed = 0
        cursor = 0  # decode-window cursor over `todo`
        if spec.prepare is not None:
            # corpus-level planning (e.g. the flow extractors' shape-bucket
            # clustering over container probes) before any decode starts
            spec.prepare(todo)
        self.clock = (StageClock(registry=self._metrics,
                                 labels={"model": self.feature_type})
                      if with_metrics else None)  # corpus-level
        session = PackedSession(self, spec)
        packer = session.packer
        self._pending_writes.clear()
        t_run = time.perf_counter()

        with maybe_profiler(self.cfg.profile_dir):
            for n, path in enumerate(paths, start=1):
                if os.path.abspath(path) in done:
                    self._ok += 1
                    resumed += 1
                    if progress:
                        progress(n, len(paths))
                    continue
                # cache consult precedes decode here too: a hit never enters
                # the packer (its rows were never going to dispatch)
                feats = self._cache_fetch(path)
                if self._decode_pool is not None:
                    if feats is None:
                        for p in todo[cursor : cursor + workers]:
                            self._decode_pool.schedule(p)
                    else:
                        self._decode_pool.release(path)
                    cursor += 1
                try:
                    if feats is not None:
                        self._publish_cache_hit(path, feats)
                    else:
                        session.ingest(path)
                        extracted += 1
                except KeyboardInterrupt:
                    raise
                except Exception as e:  # noqa: BLE001 — fault-barrier: the per-video isolation point (packed loop)
                    session.fail(path, e)
                finally:
                    if self._decode_pool is not None:
                        self._decode_pool.release(path)
                session.emit_completed()
                if progress:
                    progress(n, len(paths))
            session.drain(final=True)
        self._pack_stats = {
            "real_slots": packer.real_slots,
            "dispatched_slots": packer.dispatched_slots,
            "occupancy": round(packer.occupancy, 4),
            "video_clips": dict(packer.video_clips),
            "buckets": packer.bucket_stats(),
            "stale_flushes": packer.stale_flushes,
            # host bytes staged per dispatched device batch (the wire-format
            # counter the bench's uint8-vs-float32_wire ratio reads)
            "staged_bytes": packer.staged_bytes,
            # paged dispatch (parallel/pages.py): page count and the deepest
            # observed in-flight ring — the bench's batches-in-flight proof
            "pages_dispatched": packer.pages_dispatched,
            "max_in_flight": packer.max_in_flight,
        }
        if self.clock is not None:
            # per-stage wall seconds for the whole corpus (metrics runs only)
            # — the bench's device_preproc scenario reads the decode stage
            # from here to show decode-pool relief with the flag on
            self._pack_stats["stage_seconds"] = {
                k: round(v, 4) for k, v in self.clock.seconds.items()}
        if with_metrics:
            dt = time.perf_counter() - t_run
            if self.clock is not None:
                # the stage report carries pack_occupancy; run.py prints the
                # canonical standalone occupancy line (once) after the run
                print(self.clock.report(
                    f"packed corpus ({extracted} videos)", dt))
                # ROADMAP item 4: pin the decode-starvation signal — padding
                # burned while the run sat blocked on decode means the decode
                # pool, not the mesh, is the ceiling
                starved = decode_starvation_warning(
                    occupancy=packer.occupancy,
                    decode_seconds=self.clock.seconds.get("decode", 0.0),
                    wall=dt, stale_flushes=packer.stale_flushes,
                    transfer_seconds=self.clock.seconds.get("transfer", 0.0))
                if starved:
                    print(starved, file=sys.stderr)
            hits = f", {self._cache.hits} cache hit(s)" if self._cache else ""
            print(f"extracted {extracted}/{len(paths)} videos "
                  f"({resumed} resumed{hits}) in {dt:.2f}s")
        self.clock = None
        return self._ok


class PackedSession:
    """A live packed run: one :class:`..parallel.packer.CorpusPacker` plus the
    per-video ingest → finalize → write machinery that used to live inline in
    :meth:`Extractor._run_packed`.

    Factored out so the run loop is *resumable against a live queue*: the
    batch CLI creates one session per ``run()`` and calls :meth:`drain` after
    the last video, while the serving daemon (:mod:`..serve`) keeps ONE
    session alive for its whole lifetime — slot queues stay warm across
    requests, :meth:`ingest` is called per scheduled video in whatever order
    the tenant scheduler decides, and :meth:`drain` runs only at queue-idle
    flushes and graceful shutdown.

    ``on_done(path)`` / ``on_failed(path, exc)`` fire after the shared
    accounting (done/failure manifests, counters) — the daemon's per-request
    and per-tenant bookkeeping. ``forget_completed=True`` additionally drops
    the packer's per-video stats as each video resolves, bounding memory over
    an unbounded request stream (batch runs keep them for ``_pack_stats``).

    ``packer``/``model``: the multi-model serving layer
    (:class:`MultiModelSessions`) passes an already-built SHARED packer and
    registers this session's spec under its feature-type name — every
    co-resident model's session then feeds one ``(model, geometry)``-keyed
    packer on one mesh. Default (batch runs): build a private single-spec
    packer, keys unscoped.
    """

    def __init__(self, ex: Extractor, spec, on_done=None, on_failed=None,
                 forget_completed: bool = False, packer=None,
                 model: Optional[str] = None):
        from ..parallel.packer import CorpusPacker

        self.ex = ex
        self.spec = spec
        self.model = model
        if packer is None:
            packer = CorpusPacker(spec, wait=ex._wait, clock=ex.clock,
                                  flush_age=ex.cfg.pack_flush_age,
                                  staging=ex._staging, journal=ex._journal,
                                  metrics=ex._metrics)
            if model is not None:
                packer.register_model(model, spec)
        else:
            packer.register_model(model, spec)
        self.packer = packer
        self._on_done = on_done
        self._on_failed = on_failed
        self._forget = forget_completed

    # --- ingest ---------------------------------------------------------------

    def ingest(self, path: str, retries: Optional[int] = None) -> None:
        """Drain one video's clip stream into the packer.

        ``retries`` bounds IN-PLACE re-attempts (None = the config budget;
        the daemon passes 0 and re-enqueues transient failures through its
        scheduler instead of sleeping backoffs in the serving hot loop).
        Raises on terminal failure — the caller owns the fault barrier and
        must then call :meth:`fail` (or re-enqueue after ``packer.discard``).
        """
        ex = self.ex
        if retries is None:
            retries = ex.cfg.retries

        def on_retry(exc, attempt, delay):
            err_class, _ = classify(exc)
            print(f"[{err_class}] attempt {attempt} failed for {path}: "
                  f"{exc}; retrying in {delay:.2g}s")
            # the retry decodes fresh and repacks from clip 0: the failed
            # attempt's queued/dispatched slots are orphaned by discard()
            self.packer.discard(path)
            if ex._decode_pool is not None:
                ex._decode_pool.release(path)

        with ex._span("extract", video=path):
            retry_call(
                lambda: self._drain_stream(path),
                RetryPolicy(attempts=retries + 1,
                            base_delay=ex.cfg.retry_backoff),
                on_retry=on_retry,
            )

    def _drain_stream(self, path: str) -> None:
        """One attempt at one video: pack every clip of its stream."""
        timeout = self.ex.cfg.video_timeout
        packer = self.packer
        deadline = (time.perf_counter() + timeout) if timeout else None
        fault_point("extract", path)
        info, clips = self.spec.open_clips(path)
        packer.begin(path, info, model=self.model)
        try:
            for clip in clips:
                packer.add(path, clip)
                if deadline is not None and time.perf_counter() > deadline:
                    raise VideoTimeoutError(
                        f"{path}: packed clip stream exceeded "
                        f"--video_timeout ({timeout:.3g}s); failing this "
                        f"video")
        finally:
            # an abandoned generator's cleanup (temp-wav deletion, capture
            # release) must run before any retry re-opens the same path,
            # not whenever GC collects the frame
            close = getattr(clips, "close", None)
            if close is not None:
                close()
        packer.finish(path)

    def fail(self, path: str, e: BaseException) -> None:
        """Terminal per-video failure: orphan its slots, run the accounting."""
        self.packer.discard(path)
        self._video_failed(path, e)

    # --- results --------------------------------------------------------------

    def emit_completed(self, reap_limit: int = 1) -> None:
        """Finalize every video whose last clip's features have landed."""
        ex = self.ex
        for asm in self.packer.pop_completed(model=self.model):
            try:
                feats = self.spec.finalize(
                    asm.video, asm.stacked(self.spec.empty_row_shape),
                    asm.info)
                handle = ex._submit_outputs(asm.video, feats)
            except KeyboardInterrupt:
                raise
            except Exception as e:  # noqa: BLE001 — fault-barrier: the finalize/write arm of the packed per-video isolation point
                asm.release()
                self._video_failed(asm.video, e)
                self._forget_video(asm.video)
                continue
            # rows are views into whole fetched batches; finalize copied
            # what it needed, so release them now (long-run memory bound)
            asm.release()
            if handle is not None:
                ex._pending_writes.append((asm.video, handle))
            else:
                ex._mark_succeeded(asm.video)
                if self._on_done is not None:
                    self._on_done(asm.video)
            self._forget_video(asm.video)
        ex._reap_writes(reap_limit, on_done=self._on_done,
                        on_failed=self._on_failed)

    def drain(self, final: bool = False) -> None:
        """Dispatch partial shape queues (zero-padded tails), resolve the
        in-flight batches, and fail the videos whose rows a co-packed batch
        failure lost.

        The batch loop calls this once after the last video (``final=True``
        also reaps every pending write); the daemon calls it with
        ``final=False`` whenever the ingest queue goes idle — latency over
        occupancy when there is nothing left to pack with — and once more at
        graceful shutdown. (A multi-model daemon flushes the SHARED packer
        once and then runs each session's :meth:`_resolve_drained` —
        :meth:`MultiModelSessions.drain`.)
        """
        self._resolve_drained(final, _contained_flush(self.packer))
        self.packer.clear_flush_causes()

    def _resolve_drained(self, final: bool, flush_error) -> None:
        """Post-flush resolution for THIS session's model: finalize what
        completed, fail the videos whose rows a co-packed batch failure
        lost (each wearing only its own buckets' recorded causes)."""
        packer = self.packer
        self.emit_completed(reap_limit=0 if final else 1)
        for asm in packer.drain_incomplete(model=self.model):
            # rows lost to a failed co-packed batch (mid-run, at a stale
            # flush, or at this flush): fail each contributing video so it
            # lands in the failure manifest (DeviceError is transient — a
            # --retry_failed pass reprocesses exactly these) instead of
            # crashing the run or silently denting the return value
            causes = packer.flush_causes(asm.video)
            if flush_error is not None:
                causes.append(str(flush_error))
            cause = f": {'; '.join(causes)}" if causes else ""
            asm.release()
            self._video_failed(asm.video, DeviceError(
                f"{asm.video}: a co-packed device batch failed before "
                f"this video's clips resolved{cause}; rerun with "
                "--retry_failed"))
            self._forget_video(asm.video)

    # --- shared accounting ----------------------------------------------------

    def _video_failed(self, path: str, e: BaseException) -> None:
        # the daemon's hook runs FIRST: _fail may raise CircuitBreakerTripped
        # (batch-mode --max_failures) and the request bookkeeping must not be
        # skipped by the unwind. A truthy return CLAIMS the failure — the
        # daemon re-enqueues a transient victim (a co-packed batch failure,
        # a failed async write) through its scheduler instead of recording a
        # terminal failure here.
        if self._on_failed is not None and self._on_failed(path, e):
            return
        self.ex._fail(path, e)

    def _forget_video(self, path: str) -> None:
        if self._forget:
            self.packer.forget(path)


def _contained_flush(packer):
    """Flush ``packer``, returning (not raising) any non-dispatch failure.

    Tail-batch device failures are contained per bucket inside ``flush()``
    and surface as flush_causes on the drained victims; this wrapper is the
    safety net for failures outside that containment."""
    try:
        packer.flush()
        return None
    except KeyboardInterrupt:
        raise
    except Exception as e:  # noqa: BLE001 — fault-barrier: the corpus-flush arm of the per-video isolation point
        return e


# Flags that shape ONE model's windows/geometry/streams: reset to their
# dataclass defaults for a co-loaded serving model, so each model resolves
# its own reference behavior (an i3d daemon's resolved stack_size=64, or a
# primary-only --extraction_fps that r21d would reject outright, must not
# leak into a co-resident model's derived config).
_MODEL_SCOPED_FIELDS = ("stack_size", "step_size", "streams",
                        "extraction_fps", "side_size",
                        "resize_to_smaller_edge", "i3d_pre_crop_size",
                        "i3d_crop_size")


def derive_model_config(cfg: ExtractionConfig, model: str) -> ExtractionConfig:
    """The config a co-loaded serving model (``--serve_models``) runs under.

    Same flag surface as the daemon's primary config, with the model-scoped
    fields (``_MODEL_SCOPED_FIELDS``) RESET to their defaults so each model
    resolves its own reference behavior. Explicit per-model overrides
    therefore apply only to the primary ``--feature_type``; co-loaded
    models run their reference geometry."""
    import dataclasses

    defaults = {f.name: f.default for f in dataclasses.fields(cfg)
                if f.name in _MODEL_SCOPED_FIELDS}
    return cfg.replace(feature_type=model, **defaults)


class MultiModelSessions:
    """Co-resident models on one mesh: per-model :class:`PackedSession`\\ s
    over ONE shared ``(model, geometry)``-keyed packer (docs/serving.md).

    The serving daemon's session layer (ROADMAP item 2): the primary
    extractor (already constructed, run resources open) is joined by
    lazily-constructed extractors for each co-loaded feature type — built on
    first traffic, so a daemon configured for three models but seeing two
    pays nothing for the third — all sharing the primary's mesh runner, host
    staging ring (its geometry cap scaled by the loaded model count), async
    output writer, decode pool (rerouted per path to the owning model's host
    transform), service clock, and feature-cache store. Outputs, manifests,
    and cache fingerprints stay per model: each extractor keeps its own
    ``<output>/<feature_type>/`` tree, so a two-model daemon's outputs are
    byte-identical to the corresponding single-model daemons'.

    Dispatch interleaving lives in the shared packer (round-robin across
    models whenever several have ready batches); arrival-order interleaving
    comes from the tenant scheduler, which stays global across tenants —
    fairness is never siloed per model.
    """

    def __init__(self, primary: Extractor, models: Sequence[str],
                 on_done=None, on_failed=None, factory=None,
                 primary_spec=None):
        from ..parallel.packer import CorpusPacker

        self.primary = primary
        self.models = tuple(models)
        self._on_done = on_done
        self._on_failed = on_failed
        self._factory = factory if factory is not None else self._build_real
        if len(self.models) > 1:
            # each co-resident model brings its own working set of batch
            # geometries — scale the shared ring's cap so model B's buckets
            # don't thrash model A's staged buffers out of the ring
            primary._staging = HostStagingRing(
                depth=max(primary.cfg.prefetch_depth, 1) + 2,
                on_wait=primary._transfer_wait,
                max_geometries=(HostStagingRing.DEFAULT_MAX_GEOMETRIES
                                * len(self.models)))
        self.packer = CorpusPacker(
            wait=primary._wait, clock=primary.clock,
            flush_age=primary.cfg.pack_flush_age, staging=primary._staging,
            journal=primary._journal, metrics=primary._metrics)
        self._extractors: Dict[str, Extractor] = {
            primary.feature_type: primary}
        # path → extractor, for the shared decode pool's router; written on
        # the daemon thread at schedule time, read by pool workers at decode
        # start (schedule() happens-before the worker thread starts)
        self._ex_for_path: Dict[str, Extractor] = {}
        self._pool = None  # a pool this layer created (primary had none)
        # the daemon validates the primary spec BEFORE opening run resources
        # (so a spec-less config errors without leaking pool threads) and
        # passes it via primary_spec; the re-check here covers callers that
        # construct this layer directly
        spec = primary_spec if primary_spec is not None \
            else primary.pack_spec()
        if spec is None:
            raise ValueError(
                f"--serve needs a packing path, but {primary.feature_type} "
                "has none under this config (--show_pred and the "
                "single-clip frame-sharded flow sandwich are batch-only)")
        self._sessions: Dict[str, PackedSession] = {
            primary.feature_type: PackedSession(
                primary, spec, on_done=on_done, on_failed=on_failed,
                forget_completed=True, packer=self.packer,
                model=primary.feature_type)}
        if primary._decode_pool is not None and len(self.models) > 1:
            primary._decode_pool.set_opener(self._open_routed)
            primary._decode_pool.set_segmenter(self._plan_routed,
                                               self._open_segment_routed)

    # --- lazy model construction ---------------------------------------------

    def _build_real(self, model: str) -> Extractor:
        from . import get_extractor

        return get_extractor(derive_model_config(self.primary.cfg, model))

    def extractor(self, model: str) -> Extractor:
        """The model's extractor, constructed (and wired into the shared
        resources) on first use. Raises on an unknown model name or a
        construction failure — the daemon turns that into a clean per-video
        failure, never a crash."""
        ex = self._extractors.get(model)
        if ex is not None:
            return ex
        if model not in self.models:
            raise ValueError(f"feature_type {model!r} is not loaded "
                             f"(serving: {', '.join(self.models)})")
        primary = self.primary
        with _shared_construction(runner=primary.runner,
                                  staging=primary._staging,
                                  cache=primary._cache,
                                  journal=primary._journal,
                                  metrics=primary._metrics):
            ex = self._factory(model)
        ex.clock = primary.clock
        ex._writer = primary._writer
        ex._decode_pool = (self._shared_pool()
                           if ex.uses_frame_stream else None)
        spec = ex.pack_spec()
        if spec is None:
            raise ValueError(
                f"feature_type {model!r} has no packing path under this "
                "config; it cannot be served")
        self._sessions[model] = PackedSession(
            ex, spec, on_done=self._on_done, on_failed=self._on_failed,
            forget_completed=True, packer=self.packer, model=model)
        self._extractors[model] = ex
        return ex

    def peek_extractor(self, model: str) -> Optional[Extractor]:
        """The model's extractor if already constructed, else None (never
        triggers construction — cleanup paths must stay cheap)."""
        return self._extractors.get(model)

    def session(self, model: str) -> PackedSession:
        self.extractor(model)
        return self._sessions[model]

    # --- shared decode pool ----------------------------------------------------

    @property
    def decode_pool(self):
        return self.primary._decode_pool or self._pool

    def _shared_pool(self):
        """The one decode pool all frame-stream models share (None when the
        config runs inline decode). Created here when the primary model does
        not consume the frame stream but a co-loaded model does."""
        if self.primary._decode_pool is not None:
            return self.primary._decode_pool
        if self._pool is None and self.primary._decode_workers > 1:
            self._pool = DecodePrefetcher(self._open_routed,
                                          self.primary._decode_workers,
                                          journal=self.primary._journal)
            self._pool.set_segmenter(self._plan_routed,
                                     self._open_segment_routed)
        return self._pool

    def _open_routed(self, path: str):
        """Pool opener: decode ``path`` with its owning model's transform."""
        ex = self._ex_for_path.get(path, self.primary)
        return ex._open_inline(path)

    def _plan_routed(self, path: str, max_segments: int):
        """Pool segment planner: route to the path's owning model's policy."""
        ex = self._ex_for_path.get(path, self.primary)
        return ex._plan_inline(path, max_segments)

    def _open_segment_routed(self, plan, index: int):
        """Pool segment opener: the plan's source path names the owner."""
        ex = self._ex_for_path.get(plan.source_meta.path, self.primary)
        return ex._open_segment_inline(plan, index)

    def schedule_decode(self, path: str, model: str) -> None:
        """Prefetch-hint ``path`` on the shared pool under its model's
        decode transform. Hints never CONSTRUCT a model (weights + compile
        on the daemon thread would stall the currently-popped job): a
        not-yet-built model's jobs simply decode unhinted until their first
        pop pays construction. No-op for non-frame-stream models."""
        ex = self._extractors.get(model)
        if ex is None:
            return
        pool = ex._decode_pool
        if pool is None or not ex.uses_frame_stream:
            return
        self._ex_for_path[path] = ex
        pool.schedule(path)

    def release_decode(self, path: str) -> None:
        """Cancel/forget a path's decode on the shared pool (idempotent)."""
        self._ex_for_path.pop(path, None)
        pool = self.decode_pool
        if pool is not None:
            pool.release(path)

    # --- session routing -------------------------------------------------------

    def ingest(self, path: str, model: str, retries=None) -> None:
        self.session(model).ingest(path, retries=retries)

    def fail(self, path: str, model: str, e: BaseException) -> None:
        self.session(model).fail(path, e)

    def emit_completed(self, reap_limit: int = 1) -> None:
        for s in list(self._sessions.values()):
            s.emit_completed(reap_limit=reap_limit)

    def drain(self, final: bool = False) -> None:
        """Flush the shared packer ONCE (interleaved round-robin across
        models), then resolve each model's completions and drained victims
        — healthy models' videos finish even when one model's bucket died."""
        flush_error = _contained_flush(self.packer)
        for s in list(self._sessions.values()):
            s._resolve_drained(final, flush_error)
        self.packer.clear_flush_causes()

    # --- aggregate accounting --------------------------------------------------
    # dict(self._extractors) snapshots atomically (C-level, under the GIL):
    # the serve socket's stats op reads these from the API thread while the
    # daemon thread lazily registers a new model — Python-level iteration
    # over the live dict could raise "changed size during iteration"

    @property
    def ok(self) -> int:
        return sum(ex._ok for ex in dict(self._extractors).values())

    @property
    def failures(self) -> int:
        return sum(ex._failures for ex in dict(self._extractors).values())

    def pending_writes(self) -> int:
        return sum(len(ex._pending_writes)
                   for ex in dict(self._extractors).values())

    def model_counts(self) -> Dict[str, Dict[str, int]]:
        """Per-model completion counters for the serve stats op."""
        return {m: {"videos_ok": ex._ok, "videos_failed": ex._failures}
                for m, ex in sorted(dict(self._extractors).items())}

    def close(self) -> None:
        """Tear down: the primary closes the shared pool + writer (draining
        every model's queued writes), then each co-loaded extractor accounts
        its own abandoned handles and prunes its own failure manifest."""
        primary = self.primary
        secondaries = [ex for ex in self._extractors.values()
                       if ex is not primary]
        for ex in secondaries:
            ex._decode_pool = None  # shared (or never owned): primary closes
        if self._pool is not None:
            self._pool.shutdown()
            self._pool = None
        for ex in secondaries:
            ex._journal = None  # shared: the primary closes it (after its
            # own unwind arms have emitted their last events)
        primary._close_run_resources()
        for ex in secondaries:
            ex._writer = None  # the shared writer is closed and drained
            ex._reap_abandoned_writes()
            ex._prune_succeeded(ex._succeeded)


def pad_batch(arr: np.ndarray, batch_size: int) -> np.ndarray:
    """Zero-pad the leading axis to ``batch_size`` (static shapes: one XLA compile
    per geometry instead of one per partial tail batch)."""
    n = arr.shape[0]
    if n == batch_size:
        return arr
    if n > batch_size:
        raise ValueError(f"batch of {n} exceeds batch_size {batch_size}")
    pad = np.zeros((batch_size - n,) + arr.shape[1:], arr.dtype)
    return np.concatenate([arr, pad], axis=0)
