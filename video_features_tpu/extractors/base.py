"""Shared extraction pipeline skeleton.

Factors the loop every reference extractor re-implements (``extract_*.py``): iterate
videos with a per-video fault barrier (log & continue — ``extract_i3d.py:107-117``),
hand each finished feature dict to the output action, track progress. Adds what the
reference lacks: a done-manifest for resume and device-count awareness.
"""

from __future__ import annotations

import abc
import os
from typing import Dict, List, Optional, Sequence

import numpy as np

from ..config import ExtractionConfig, resolve_model_defaults
from ..io.filelist import form_video_list
from ..io.output import (
    action_on_extraction,
    feature_output_dir,
    load_done_set,
    mark_done,
)


class Extractor(abc.ABC):
    """Base class for all per-model pipelines."""

    def __init__(self, cfg: ExtractionConfig):
        cfg = resolve_model_defaults(cfg)
        cfg.validate()
        self.cfg = cfg
        self.feature_type = cfg.feature_type
        # per-feature-type subdirs, as the reference joins them (extract_i3d.py:77-78)
        self.output_dir = feature_output_dir(cfg.output_path, cfg.feature_type)
        self.tmp_dir = os.path.join(cfg.tmp_path, cfg.feature_type)

    # --- per-model API ---

    @abc.abstractmethod
    def extract(self, video_path: str) -> Dict[str, np.ndarray]:
        """Extract features for one video; keys become output-file suffixes."""

    # --- shared driver ---

    def video_list(self) -> List[str]:
        return form_video_list(self.cfg.video_paths, self.cfg.file_with_video_paths)

    def run(self, video_paths: Optional[Sequence[str]] = None, progress=None) -> int:
        """Process all videos with the per-video fault barrier; returns #succeeded.

        ``progress``: optional callable invoked after each video (done, total).
        """
        paths = list(video_paths) if video_paths is not None else self.video_list()
        done = load_done_set(self.output_dir) if self.cfg.resume else set()
        ok = 0
        for n, path in enumerate(paths, start=1):
            if os.path.abspath(path) in done:
                ok += 1
                if progress:
                    progress(n, len(paths))
                continue
            try:
                feats_dict = self.extract(path)
                action_on_extraction(feats_dict, path, self.output_dir, self.cfg.on_extraction)
                if self.cfg.on_extraction == "save_numpy":
                    mark_done(self.output_dir, path, feats_dict.keys())
                ok += 1
            except KeyboardInterrupt:
                raise
            except Exception as e:  # noqa: BLE001 — per-video fault barrier
                print(e)
                print(f"Extraction failed at: {path} with error (↑). Continuing extraction")
            if progress:
                progress(n, len(paths))
        return ok


def pad_batch(arr: np.ndarray, batch_size: int) -> np.ndarray:
    """Zero-pad the leading axis to ``batch_size`` (static shapes: one XLA compile
    per geometry instead of one per partial tail batch)."""
    n = arr.shape[0]
    if n == batch_size:
        return arr
    if n > batch_size:
        raise ValueError(f"batch of {n} exceeds batch_size {batch_size}")
    pad = np.zeros((batch_size - n,) + arr.shape[1:], arr.dtype)
    return np.concatenate([arr, pad], axis=0)
