"""Shared extraction pipeline skeleton.

Factors the loop every reference extractor re-implements (``extract_*.py``): iterate
videos with a per-video fault barrier (log & continue — ``extract_i3d.py:107-117``),
hand each finished feature dict to the output action, track progress. Adds what the
reference lacks: a done-manifest for resume and device-count awareness.
"""

from __future__ import annotations

import abc
import os
import time
from typing import Dict, List, Optional, Sequence

import numpy as np

from ..config import ExtractionConfig, resolve_model_defaults
from ..io.filelist import form_video_list
from ..io.output import (
    action_on_extraction,
    feature_output_dir,
    load_done_set,
    mark_done,
)
from ..io.video import open_video
from ..parallel import MeshRunner
from ..parallel.pipeline import DecodePrefetcher
from ..utils.metrics import StageClock, maybe_profiler, metrics_enabled


class Extractor(abc.ABC):
    """Base class for all per-model pipelines."""

    # True for models that consume the open_video frame stream (resnet50, flow,
    # i3d); r21d (whole-video torchvision-style decode) and vggish (audio)
    # don't, so the decode pool would prefetch frames nobody reads
    uses_frame_stream = False

    def __init__(self, cfg: ExtractionConfig):
        cfg = resolve_model_defaults(cfg)
        cfg.validate()
        self.cfg = cfg
        self.feature_type = cfg.feature_type
        # per-feature-type subdirs, as the reference joins them (extract_i3d.py:77-78)
        self.output_dir = feature_output_dir(cfg.output_path, cfg.feature_type)
        self.tmp_dir = os.path.join(cfg.tmp_path, cfg.feature_type)
        # data-parallel mesh every device step runs on; --num_devices selects the
        # mesh size (None = all local devices), replacing the reference's
        # thread-per-GPU dispatch (/root/reference/main.py:37-47)
        self.runner = MeshRunner(cfg.num_devices, cfg.matmul_precision)
        # per-video stage clock; active only when metrics are enabled (run())
        self.clock: Optional[StageClock] = None
        # cross-video decode pool; created by run() when --decode_workers > 1
        self._decode_pool: Optional[DecodePrefetcher] = None

    # --- per-model API ---

    @abc.abstractmethod
    def extract(self, video_path: str) -> Dict[str, np.ndarray]:
        """Extract features for one video; keys become output-file suffixes."""

    def _host_transform(self, rgb: np.ndarray) -> np.ndarray:
        """Per-frame host transform applied during decode (override per model)."""
        return rgb

    # --- decode (frame-stream models route through the prefetcher) ---

    def _open_inline(self, video_path: str):
        return open_video(
            video_path,
            extraction_fps=self.cfg.extraction_fps,
            tmp_path=self.tmp_dir,
            keep_tmp_files=self.cfg.keep_tmp_files,
            use_ffmpeg=self.cfg.use_ffmpeg,
            transform=self._host_transform,
        )

    def _open_video(self, video_path: str):
        """(meta, frames_iter) — prefetched by a decode worker when the pool
        is active (``--decode_workers``), else decoded inline."""
        if self._decode_pool is not None:
            return self._decode_pool.get(video_path)
        return self._open_inline(video_path)

    # --- observability hooks (no-ops unless metrics are enabled) ---

    def _timed_frames(self, frames_iter):
        """Attribute host time blocked on decode/transform to the 'decode' stage."""
        if self.clock is None:
            return frames_iter
        return self.clock.timed_iter(frames_iter, "decode")

    def _wait(self, device_out) -> np.ndarray:
        """Gather a device result, attributing blocked time to 'device_wait'."""
        if self.clock is None:
            return np.asarray(device_out)
        with self.clock.stage("device_wait"):
            return np.asarray(device_out)

    def _throttle(self, outputs: Sequence) -> None:
        """Bound in-flight device work when per-batch results stay on device.

        Deferring the host fetch to one per video removes the implicit
        backpressure the old per-batch ``np.asarray`` provided; without a bound
        the host dispatches every batch of a long video ahead of compute and
        pins them all in HBM. Blocking on the (prefetch_depth+1)-oldest output
        keeps at most ~prefetch_depth batches outstanding.
        """
        depth = max(self.cfg.prefetch_depth, 1)
        if len(outputs) > depth:
            import jax

            jax.block_until_ready(outputs[-depth - 1])

    # --- shared driver ---

    def video_list(self) -> List[str]:
        return form_video_list(self.cfg.video_paths, self.cfg.file_with_video_paths)

    def run(self, video_paths: Optional[Sequence[str]] = None, progress=None) -> int:
        """Process all videos with the per-video fault barrier; returns #succeeded.

        ``progress``: optional callable invoked after each video (done, total).
        """
        paths = list(video_paths) if video_paths is not None else self.video_list()
        done = load_done_set(self.output_dir) if self.cfg.resume else set()
        with_metrics = metrics_enabled(self.cfg.profile_dir)
        workers = self.cfg.decode_workers
        if workers > 1 and self.uses_frame_stream:
            self._decode_pool = DecodePrefetcher(self._open_inline, workers)
        elif workers > 1:
            print(f"--decode_workers ignored: {self.feature_type} does not "
                  "consume the frame stream (whole-video / audio decode)")
        try:
            return self._run_loop(paths, done, with_metrics, progress)
        finally:
            # KeyboardInterrupt / a raising progress callback must not leak
            # decode workers busy-waiting on full queues
            if self._decode_pool is not None:
                self._decode_pool.shutdown()
                self._decode_pool = None

    def _run_loop(self, paths, done, with_metrics, progress) -> int:
        todo = [p for p in paths if os.path.abspath(p) not in done]
        workers = self.cfg.decode_workers
        ok = 0
        extracted = 0  # excludes resume-skipped videos (throughput honesty)
        cursor = 0  # decode-window cursor over `todo`
        t_run = time.perf_counter()
        with maybe_profiler(self.cfg.profile_dir):
            for n, path in enumerate(paths, start=1):
                if os.path.abspath(path) in done:
                    ok += 1
                    if progress:
                        progress(n, len(paths))
                    continue
                if self._decode_pool is not None:
                    # keep `workers` videos decoding ahead of the consumer
                    for p in todo[cursor : cursor + workers]:
                        self._decode_pool.schedule(p)
                    cursor += 1
                self.clock = StageClock() if with_metrics else None
                t0 = time.perf_counter()
                try:
                    feats_dict = self.extract(path)
                    action_on_extraction(
                        feats_dict, path, self.output_dir, self.cfg.on_extraction
                    )
                    if self.cfg.on_extraction == "save_numpy":
                        mark_done(self.output_dir, path, feats_dict.keys())
                    ok += 1
                    extracted += 1
                    if self.clock is not None:
                        print(self.clock.report(path, time.perf_counter() - t0))
                except KeyboardInterrupt:
                    raise
                except Exception as e:  # noqa: BLE001 — per-video fault barrier
                    print(e)
                    print(f"Extraction failed at: {path} with error (↑). Continuing extraction")
                finally:
                    self.clock = None
                    if self._decode_pool is not None:
                        # cancel this video's decode stream whether it was fully
                        # drained or abandoned by a compute error — an orphaned
                        # worker would pin a permit + max_buffered frames forever
                        self._decode_pool.release(path)
                if progress:
                    progress(n, len(paths))
        if with_metrics and extracted:
            dt = time.perf_counter() - t_run
            print(f"extracted {extracted}/{len(paths)} videos "
                  f"({ok - extracted} resumed) in {dt:.2f}s "
                  f"({extracted / dt:.3f} videos/sec)")
        return ok


def pad_batch(arr: np.ndarray, batch_size: int) -> np.ndarray:
    """Zero-pad the leading axis to ``batch_size`` (static shapes: one XLA compile
    per geometry instead of one per partial tail batch)."""
    n = arr.shape[0]
    if n == batch_size:
        return arr
    if n > batch_size:
        raise ValueError(f"batch of {n} exceeds batch_size {batch_size}")
    pad = np.zeros((batch_size - n,) + arr.shape[1:], arr.dtype)
    return np.concatenate([arr, pad], axis=0)
