"""ResNet-50 per-frame feature extractor.

Behavioral spec (``/root/reference/models/resnet50/extract_resnet50.py``): decode →
smaller-edge resize 256 (PIL bilinear) → center crop 224 → /255 + ImageNet normalize →
ResNet-50 with identity head → 2048-d per-frame features, batched by ``--batch_size``
with the partial tail batch processed too (``:118-143``); output keys ``resnet50``,
``fps``, ``timestamps_ms``; ``--show_pred`` prints ImageNet top-5 via the saved fc
head (``:54-58,98-101``).

TPU design: host does decode+resize+crop (uint8); the jitted device step fuses
normalize into the conv stack; the tail batch is zero-padded to the static batch
shape so XLA compiles exactly one program per run. ``--device_resize`` (or its
every-model generalization ``--device_preproc``) moves the PIL resize+crop
inside the step too (``ops/image.device_resize_crop_hwc``): raw decoded frames
ride the wire, one compiled program per decoded geometry, at a documented
tolerance vs the PIL parity path (docs/performance.md).
"""

from __future__ import annotations

from typing import Dict

import numpy as np

import jax
import jax.numpy as jnp

from ..models.resnet import ResNet50, preprocess_frames
from ..parallel import prefetch_to_device
from ..ops.image import device_resize_crop_hwc, np_center_crop_hwc, pil_edge_resize
from ..utils.labels import show_predictions_on_dataset
from ..weights.convert_torch import convert_resnet50
from ..weights.store import resolve_params
from .base import Extractor

RESIZE_SIZE = 256
CENTER_CROP_SIZE = 224


class ExtractResNet50(Extractor):
    uses_frame_stream = True
    # --device_resize: the host PIL resize+crop moves inside the jitted step
    # (ops/image.device_resize_crop_hwc) — raw decoded frames on the wire,
    # slots keyed per decoded geometry in packed runs; tolerance-gated vs
    # the bit-parity host path (docs/performance.md)
    supports_device_resize = True
    # --device_preproc is the same path here: resnet50's only host preprocess
    # IS the resize+crop, so the general flag folds into _device_resize
    # (cache/key.py resolves the two flags identically for resnet50)
    supports_device_preproc = True

    def __init__(self, cfg):
        super().__init__(cfg)
        self._device_resize = cfg.device_resize or cfg.device_preproc
        # round the user batch up to a multiple of the mesh size so the sharded
        # leading axis always divides evenly (tail rows are zero-padded + trimmed)
        self.batch_size = self.runner.device_batch(cfg.batch_size)
        self.dtype = jnp.bfloat16 if cfg.dtype == "bfloat16" else jnp.float32
        self.model = ResNet50(dtype=self.dtype)
        self.params = self.runner.put_replicated(
            resolve_params(
                "resnet50",
                convert_torch_fn=convert_resnet50,
                init_fn=self._random_init,
            )
        )
        if cfg.show_pred and "fc" not in self.params:
            raise ValueError(
                "--show_pred needs the classifier head, but the resolved resnet50 "
                "checkpoint has no 'fc' params (feature-only checkpoint)"
            )
        self._step = self.runner.jit(self._forward)

    def _random_init(self):
        from ..weights.store import random_params_like

        rng = jax.random.PRNGKey(0)
        dummy = jnp.zeros((1, CENTER_CROP_SIZE, CENTER_CROP_SIZE, 3), jnp.uint8)
        init = lambda r, d: self.model.init(r, d, features=False)  # noqa: E731
        return random_params_like(init, rng, dummy)["params"]

    def _forward(self, params, frames_u8):
        if self._device_resize:
            # raw decoded frames in: the edge resize + crop run fused into
            # the step (static geometry per compile — each decoded geometry
            # is its own program, like the i3d aspect-ratio queues)
            frames_u8 = device_resize_crop_hwc(
                frames_u8, RESIZE_SIZE, CENTER_CROP_SIZE)
        x = preprocess_frames(frames_u8, dtype=self.dtype)
        feats = self.model.apply({"params": params}, x, features=True)
        return feats.astype(jnp.float32)

    def _host_transform(self, rgb: np.ndarray) -> np.ndarray:
        if self._device_resize:
            return rgb  # ship the raw decoded frame; the step resizes
        rgb = pil_edge_resize(rgb, RESIZE_SIZE)
        return np_center_crop_hwc(rgb, CENTER_CROP_SIZE, CENTER_CROP_SIZE)

    def pack_spec(self):
        """Corpus-packing seam: every device slot is one 224² frame — or one
        RAW decoded frame under ``--device_resize``/``--device_preproc``,
        where queues key by decoded geometry — so same-shape clips share a
        queue and the tail batch of video N fills with the head of video
        N+1. Per-row features are byte-identical to the per-video loop on
        the 224² wire (no cross-sample ops, same jitted program); the raw
        wire is ulp-level instead — pages run the resize prologue at
        page_rows, a different static shape than the per-video batch, and
        XLA's f32 resize is not bitwise-stable across shapes
        (tests/test_device_preproc.py pins 1e-5 relative)."""
        if self.cfg.show_pred:
            return None  # debug path prints per-batch top-5 in video order
        from ..parallel.packer import PackSpec

        # Ragged paged dispatch (--paged_batching): always on. Packer queues
        # are keyed by clip shape, so under --device_resize/--device_preproc
        # each raw decoded geometry pages through its OWN queue — pages never
        # co-host mixed geometries, and every queue shares one compiled
        # jit_paged family per geometry (the same multi-queue paging i3d's
        # aspect-ratio buckets already exercise).
        paged = self._paged_fields(self._forward, self.params,
                                   self.batch_size)

        def open_clips(path):
            meta, frames = self._open_video(path)
            info = {"fps": meta.fps, "timestamps_ms": []}

            def clips():
                for rgb, pos in self._timed_frames(frames):
                    info["timestamps_ms"].append(pos)
                    yield rgb

            return info, clips()

        def step(frames_u8):
            # _put attributes dispatch time + staged bytes to the 'transfer'
            # stage; the packer commits the staged buffer after the step
            return self._step(self.params, self._put(frames_u8))

        def finalize(path, rows, info):
            return {
                self.feature_type: rows,
                "fps": np.array(info["fps"]),
                "timestamps_ms": np.array(info["timestamps_ms"]),
            }

        return PackSpec(batch_size=self.batch_size, empty_row_shape=(2048,),
                        open_clips=open_clips, step=step, finalize=finalize,
                        **paged)

    def extract(self, video_path: str) -> Dict[str, np.ndarray]:
        meta, frames = self._open_video(video_path)
        timestamps_ms = []
        valid_counts = []

        def batches():
            # frames are stacked into reusable staging-ring buffers (the
            # prefetcher's commit hook guards them until their device_put
            # resolves) — no fresh np.stack/pad_batch allocation per batch
            batch = []
            for rgb, pos in self._timed_frames(frames):
                timestamps_ms.append(pos)
                batch.append(rgb)
                if len(batch) == self.batch_size:
                    valid_counts.append(len(batch))
                    yield self._stage_rows(batch)
                    batch = []
            if batch:  # partial tail batch (reference :139-141), zero-padded
                valid_counts.append(len(batch))
                yield self._stage_rows(batch, self.batch_size)

        if self.cfg.show_pred:
            # debug path: fetch the fc head ONCE per video (device_wait-
            # accounted), not per batch — the head is ~8 MB and re-fetching
            # it every batch was an unaccounted host sync in the step loop
            fc = self.params["fc"]
            fc_kernel = self._wait(fc["kernel"])
            fc_bias = self._wait(fc["bias"])

        vid_feats = []
        # decode of batch k+1 overlaps device compute of batch k; the transfer
        # target is the mesh batch sharding, so frames land pre-split per device.
        # Per-batch features STAY on device — one host fetch per video (each
        # host sync costs ~100-200 ms on a tunneled TPU)
        for i, device_batch in enumerate(
            prefetch_to_device(
                batches(),
                sharding=self.runner.batch_sharding,
                depth=self.cfg.prefetch_depth,
                clock=self.clock,
                commit=self._staging.commit,
            )
        ):
            feats = self._step(self.params, device_batch)[: valid_counts[i]]
            if self.cfg.show_pred:  # debug mode: fetch once, reuse for logits
                feats = self._wait(feats)
                logits = feats @ fc_kernel + fc_bias
                show_predictions_on_dataset(logits, "imagenet")
            vid_feats.append(feats)
            self._throttle(vid_feats)

        if not vid_feats:
            feats = np.zeros((0, 2048), np.float32)
        elif isinstance(vid_feats[0], np.ndarray):  # show_pred fetched per batch
            feats = np.concatenate(vid_feats, axis=0)
        else:
            feats = self._wait(jnp.concatenate(vid_feats, axis=0))
        return {
            self.feature_type: feats,
            "fps": np.array(meta.fps),
            "timestamps_ms": np.array(timestamps_ms),
        }
