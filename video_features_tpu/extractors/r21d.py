"""R(2+1)D-18 clip extractor: whole-video decode → 16-frame slices → 512-d features.

Behavioral spec — ``/root/reference/models/r21d/extract_r21d.py``:
- whole video into RAM (``read_video``, ``:102``); fps re-encode forbidden by the
  reference ``sanity_check`` (enforced in :mod:`video_features_tpu.config`);
- transforms: /255 → bilinear resize (128, 171) → Kinetics normalize → center crop
  112 (``:32-38``);
- ``form_slices`` full 16-frame windows, step 16, trailing frames dropped (``:107``);
- per-slice r2plus1d_18 with identity head → 512-d; ``--show_pred`` applies the
  saved fc for Kinetics top-5 (``:111-121``);
- output: features only — the reference omits fps/timestamps for this model
  (``:123-125``), reproduced for drop-in parity.

TPU design: slices are batched ``clips_per_batch`` at a time into one jitted step
(static shapes, tail zero-padded then trimmed); preprocess runs on device fused
into the stem.
"""

from __future__ import annotations

import functools
from typing import Dict

import numpy as np

import jax
import jax.numpy as jnp

from ..io.video import decode_all
from ..models.r21d import NUM_FEATURES, R2Plus1D18, r21d_preprocess
from ..utils.labels import show_predictions_on_dataset
from ..utils.windows import form_slices
from ..weights.convert_torch import convert_r21d
from ..weights.store import resolve_params
from .base import Extractor


class ExtractR21D(Extractor):
    # --device_preproc is a documented no-op here: r21d's whole transform
    # chain (/255 → bilinear resize (128, 171) → Kinetics normalize → center
    # crop 112, r21d_preprocess) has run device-fused since the port — raw
    # native-resolution clips are ALREADY the wire format, so the general
    # flag has nothing left to move and must not print the "ignored" notice
    supports_device_preproc = True

    def __init__(self, cfg):
        super().__init__(cfg)
        cfg = self.cfg  # model defaults resolved by the base class
        self.stack_size = cfg.stack_size
        self.step_size = cfg.step_size
        # clips per device step, rounded to a multiple of the mesh size
        self.clips_per_batch = self.runner.device_batch(cfg.clips_per_batch)
        self.dtype = jnp.bfloat16 if cfg.dtype == "bfloat16" else jnp.float32
        self.model = R2Plus1D18(dtype=self.dtype)
        self.params = self.runner.put_replicated(
            resolve_params(
                "r2plus1d_18",
                convert_torch_fn=convert_r21d,
                init_fn=self._random_init,
            )
        )
        if cfg.show_pred and "fc" not in self.params:
            raise ValueError(
                "--show_pred needs the classifier head, but the resolved r2plus1d_18 "
                "checkpoint has no 'fc' params"
            )

    def _random_init(self):
        from ..weights.store import random_params_like

        dummy = jnp.zeros((1, 4, 112, 112, 3))
        init = lambda r, d: self.model.init(r, d, features=False)  # noqa: E731
        return random_params_like(init, jax.random.PRNGKey(0), dummy)["params"]

    def _forward(self, params, clips_u8):
        # (N, 16, H, W, 3) uint8 native resolution; pure per-row — the paged
        # dispatch path wraps this same body (parallel/pages.paged_program)
        n, t = clips_u8.shape[:2]
        flat = clips_u8.reshape((n * t,) + clips_u8.shape[2:])
        x = r21d_preprocess(flat, dtype=self.dtype).reshape((n, t, 112, 112, 3))
        return self.model.apply(
            {"params": params}, x, features=True).astype(jnp.float32)

    @functools.cached_property
    def _step(self):
        return self.runner.jit(self._forward)

    def pack_spec(self):
        """Corpus-packing seam: slots are ``(stack, H, W, 3)`` native-
        resolution slices, shape-keyed per video geometry — same-resolution
        videos co-pack; a mixed-resolution corpus fills one queue per
        geometry. Slots are views into the whole-video decode buffer, so a
        pending tail pins at most ``clips_per_batch - 1`` videos' buffers
        per geometry until the next same-shape video (or the corpus flush)
        dispatches them."""
        if self.cfg.show_pred:
            return None  # debug path prints per-clip top-5 in video order
        from ..parallel.packer import PackSpec

        def open_clips(path):
            _meta, frames, _ts = decode_all(
                path, extraction_fps=None, tmp_path=self.tmp_dir)
            slices = form_slices(frames.shape[0], self.stack_size,
                                 self.step_size)

            def clips():
                for s, e in slices:
                    yield frames[s:e]

            return {}, clips()

        def step(clips_u8):
            # _put: 'transfer'-stage attribution (time + staged bytes); the
            # packer commits the staged ring buffer after the step
            return self._step(self.params, self._put(clips_u8))

        def finalize(path, rows, info):
            # reference returns features only for r21d (extract_r21d.py:123-125)
            return {self.feature_type: rows}

        return PackSpec(batch_size=self.clips_per_batch,
                        empty_row_shape=(NUM_FEATURES,),
                        open_clips=open_clips, step=step, finalize=finalize,
                        **self._paged_fields(self._forward, self.params,
                                             self.clips_per_batch))

    def extract(self, video_path: str) -> Dict[str, np.ndarray]:
        meta, frames, _ts = decode_all(
            video_path,
            extraction_fps=None,  # validated off for r21d
            tmp_path=self.tmp_dir,
        )
        slices = form_slices(frames.shape[0], self.stack_size, self.step_size)
        if self.cfg.show_pred:
            # debug path: fetch the fc head ONCE per video (device_wait-
            # accounted), not per clip batch
            fc = self.params["fc"]
            fc_kernel = self._wait(fc["kernel"])
            fc_bias = self._wait(fc["bias"])
        vid_feats = []
        for i in range(0, len(slices), self.clips_per_batch):
            chunk = slices[i : i + self.clips_per_batch]
            clips = self._stage_rows([frames[s:e] for s, e in chunk],
                                     self.clips_per_batch)
            dev = self._put(clips)
            self._staging.commit(clips, dev)  # guard the ring buffer
            clips = dev
            # stays on device; one host fetch per video
            feats = self._step(self.params, clips)[: len(chunk)]
            if self.cfg.show_pred:  # debug mode: fetch once, reuse for logits
                feats = self._wait(feats)
                logits = feats @ fc_kernel + fc_bias
                for (s, e), row in zip(chunk, logits):
                    print(f"{video_path} @ frames ({s}, {e})")
                    show_predictions_on_dataset(row[None], "kinetics")
            vid_feats.append(feats)
            self._throttle(vid_feats)

        if not vid_feats:
            feats = np.zeros((0, NUM_FEATURES), np.float32)
        elif isinstance(vid_feats[0], np.ndarray):  # show_pred fetched per batch
            feats = np.concatenate(vid_feats, axis=0)
        else:
            feats = self._wait(jnp.concatenate(vid_feats, axis=0))
        # reference returns features only for r21d (extract_r21d.py:123-125)
        return {self.feature_type: feats}
