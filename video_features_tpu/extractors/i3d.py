"""I3D two-stream extractor: sliding 64-frame stacks → rgb & flow 1024-d features.

Behavioral spec — ``/root/reference/models/i3d/extract_i3d.py``:
- decode → PIL smaller-edge resize to 256 (``:25,54-59``);
- accumulate ``stack_size + 1`` frames; on a full stack run both streams, keep
  ``stack[step_size:]`` as overlap, timestamp the completed stack (``:207-215``);
  partial trailing stacks are dropped (``:216-219``);
- rgb stream: first 64 frames → center-crop 224 → [−1,1] (``:59-63,148-156``);
- flow stream: flow between consecutive frames of the *256-edge* stack — RAFT on
  replicate-padded /8 frames with NO unpadding (the 224 center crop runs on the
  padded flow: reference quirk, ``:146-148`` + ``transforms``), PWC at native 256
  size — then center-crop 224 → clamp ±20 → uint8 quantize → [−1,1] (``:64-72``);
- each stream through its own pretrained I3D → (1, 1024) per stack (``:161-164``);
- ``--show_pred``: Kinetics-400 top-5 per stack per stream (``:166-169``);
- outputs keyed by stream name (``rgb``/``flow``) + fps + timestamps.

TPU design (vs the reference's one-stack-at-a-time GPU loop, ``:139-169``):
- the ENTIRE stack step — flow net, transform sandwich, I3D — is one jitted
  program per stream, so flow maps never leave HBM between the flow net and the
  I3D conv stack;
- ``clips_per_batch`` stacks are batched into each jitted call (the reference has
  no clip batching at all) and the batch axis is sharded across the device mesh;
- host decode/stacking overlaps device compute via the prefetcher;
- ``--dtype bfloat16`` runs the I3D conv stacks in bf16 on the MXU; the flow
  nets have their own ``--flow_dtype`` knob (default fp32 for reference parity;
  bf16 keeps correlation accumulation and coordinate math fp32 — measured
  drift in tests/test_flow_bf16.py).
"""

from __future__ import annotations

import functools
import os
from typing import Dict, List

import numpy as np

import jax
import jax.numpy as jnp

from ..models.i3d import I3D, i3d_preprocess_flow, i3d_preprocess_rgb
from ..models.pwc import pwc_forward_frames, pwc_forward_frames_sharded, pwc_init_params
from ..models.raft import (
    raft_forward_frames,
    raft_forward_frames_sharded,
    raft_init_params,
)
from ..ops.image import device_edge_resize_hwc, pil_edge_resize
from ..parallel import prefetch_to_device
from ..utils.labels import show_predictions_on_dataset
from ..weights.convert_torch import convert_i3d, convert_pwc, convert_raft
from ..weights.store import resolve_params
from .base import Extractor, pad_batch

# Reference geometry (256-edge resize, 224 center crop — extract_i3d.py:25 +
# transforms) lives in config.py as the i3d_pre_crop_size/i3d_crop_size defaults.


def _center_crop_nhwc(x: jnp.ndarray, size: int) -> jnp.ndarray:
    """Reference TensorCenterCrop: floor-divide offsets (transforms.py:7-18)."""
    h, w = x.shape[-3], x.shape[-2]
    fh = (h - size) // 2
    fw = (w - size) // 2
    return x[..., fh : fh + size, fw : fw + size, :]


class ExtractI3D(Extractor):
    uses_frame_stream = True
    # --device_preproc: the host PIL 256-edge resize moves inside every
    # jitted stream body (ops/image.device_edge_resize_hwc over the whole
    # clip stack, BEFORE the /8 pad and 224 crop, which already run on
    # device) — raw decoded stacks ride the wire, queues key per decoded
    # geometry, tolerance-gated vs the PIL path (tests/test_device_preproc.py)
    supports_device_preproc = True

    def __init__(self, cfg):
        super().__init__(cfg)
        cfg = self.cfg  # model defaults resolved by the base class
        self._device_preproc = cfg.device_preproc
        self.streams = tuple(cfg.streams or ("rgb", "flow"))
        self.stack_size = cfg.stack_size
        self.step_size = cfg.step_size
        self.flow_type = cfg.flow_type
        self.pre_crop_size = cfg.i3d_pre_crop_size
        self.crop_size = cfg.i3d_crop_size
        # stacks per device step, rounded to a multiple of the mesh size
        self.clips_per_batch = self.runner.device_batch(cfg.clips_per_batch)
        self.dtype = jnp.bfloat16 if cfg.dtype == "bfloat16" else jnp.float32
        # Encode-once frame sharding: a flow-only single-clip job on a
        # multi-device mesh shards the stack's FRAME axis across devices
        # (halo exchange forms each shard's cross-shard pair —
        # models/{raft,pwc}.*_forward_frames_sharded) instead of rounding the
        # clip axis up to the mesh, where D-1 of D padded clips were pure
        # waste at video tails and the mesh idled whenever fewer clips than
        # devices were in flight. The sandwich's dominant stage (the flow
        # net) then spans the whole mesh per clip. Two-stream jobs keep clip
        # sharding: both streams consume the same device batch, and the rgb
        # stream has no frame-pair structure to shard along.
        self._flow_frame_sharded = (
            self.runner.num_devices > 1
            and self.streams == ("flow",)
            and cfg.clips_per_batch == 1
            and self.stack_size % self.runner.num_devices == 0
        )
        if (self._flow_frame_sharded and self.flow_type == "pwc"
                and cfg.flow_pair_chunk is not None):
            # the frame-sharded step decodes each shard's stack_size/D pairs
            # in one piece (no lax.map chunking); a user explicitly bounding
            # decoder memory with --flow_pair_chunk must get the path that
            # honors it rather than a silent OOM
            print("--flow_pair_chunk set: keeping the clip-sharded flow step "
                  "(the frame-sharded encode-once step does not chunk the "
                  "per-shard decode)")
            self._flow_frame_sharded = False
        if self._flow_frame_sharded:
            self.clips_per_batch = 1  # one frame-sharded clip per step

        # VFT_I3D_S2D=1 opts into the space-to-depth stem lowering; measured
        # SLOWER on v5e (the fold relayout costs more than the small-channel
        # stem conv, which XLA already runs at ~20 TF/s — tools/profile_i3d.py)
        s2d = os.environ.get("VFT_I3D_S2D") == "1"
        self.i3d = {s: I3D(modality=s, s2d_stem=s2d, dtype=self.dtype)
                    for s in self.streams}
        self.i3d_params = {
            s: self.runner.put_replicated(
                resolve_params(
                    f"i3d_{s}",
                    convert_torch_fn=convert_i3d,
                    init_fn=functools.partial(self._random_i3d, s),
                )
            )
            for s in self.streams
        }
        if "flow" in self.streams:
            if cfg.flow_pair_chunk is not None and self.flow_type == "raft":
                print("--flow_pair_chunk is PWC-only and ignored with "
                      "--flow_type raft (RAFT bounds flow memory via "
                      "--raft_corr auto)")
            if self.flow_type == "raft":
                self.flow_params = resolve_params(
                    "raft-sintel", convert_torch_fn=convert_raft,
                    init_fn=lambda: raft_init_params(seed=0))
            elif self.flow_type == "pwc":
                self.flow_params = resolve_params(
                    "pwc-sintel", convert_torch_fn=convert_pwc,
                    init_fn=lambda: pwc_init_params(seed=0))
            else:
                raise ValueError(f"unknown flow_type {self.flow_type!r}")
            # closed over by the jitted flow step (trace-time constants) — pin
            # them replicated so tracing doesn't re-transfer per compile
            self.flow_params = self.runner.put_replicated(self.flow_params)
        else:
            self.flow_params = None

    def _random_i3d(self, stream: str):
        from ..weights.store import random_params_like

        model = self.i3d[stream]
        c = 3 if stream == "rgb" else 2
        dummy = jnp.zeros((1, 16, self.crop_size, self.crop_size, c))
        init = lambda r, d: model.init(r, d, features=False)  # noqa: E731
        return random_params_like(init, jax.random.PRNGKey(0), dummy)["params"]

    # --- jitted stack steps -------------------------------------------------

    def _rgb_forward(self, params, stacks_u8):  # (N, S+1, H, W, 3) uint8
        # pure per-row stream body — jitted whole by `_rgb_step`, composed
        # (un-jitted) into the paged program by `pack_spec`
        model = self.i3d["rgb"]
        if self._device_preproc:
            # raw decoded stack in: the 256-edge resize runs fused here
            # (float32 [0,255] out; preprocess casts anyway)
            stacks_u8 = device_edge_resize_hwc(stacks_u8, self.pre_crop_size)
        x = i3d_preprocess_rgb(
            _center_crop_nhwc(stacks_u8[:, :-1], self.crop_size),
            dtype=self.dtype
        )  # (N, S, crop, crop, 3)
        feats = model.apply({"params": params}, x, features=True)
        if self.cfg.show_pred:
            _, logits = model.apply({"params": params}, x, features=False)
            return feats, logits
        return feats, None

    @functools.cached_property
    def _rgb_step(self):
        return self.runner.jit(self._rgb_forward)

    def _flow_forward(self, params, stacks_u8):  # (N, S+1, H, W, 3) uint8
        # pure per-row stream body (flow net + I3D flow stream) — jitted
        # whole by `_flow_step`, composed into the paged program by
        # `pack_spec`
        model = self.i3d["flow"]
        flow_dtype = (jnp.bfloat16 if self.cfg.flow_dtype == "bfloat16"
                      else jnp.float32)
        if self._device_preproc:
            # raw decoded stack in: resize BEFORE the shape unpack so the
            # /8 pad and the flow nets see post-resize geometry (the flow is
            # computed on the resized pre-crop stack, as on the host path)
            stacks_u8 = device_edge_resize_hwc(stacks_u8, self.pre_crop_size)
        n, sp1, h, w, _c = stacks_u8.shape
        frames = stacks_u8.astype(jnp.float32)
        # shared-frame flow: each frame is encoded ONCE and the N·S
        # consecutive pairs are formed from the per-frame features (the
        # encoder/pyramid is the flow nets' dominant stage; pair-split
        # batches would encode every interior frame twice). The clip axis
        # stays leading and mesh-sharded: each device flows its own clips.
        if self.flow_type == "raft":
            # replicate-pad to /8 and, like the reference, never unpad: the
            # 224 center crop below runs on the padded flow
            ph, pw = (8 - h % 8) % 8, (8 - w % 8) % 8
            pads = ((0, 0), (0, 0),
                    (ph // 2, ph - ph // 2), (pw // 2, pw - pw // 2), (0, 0))
            flow = raft_forward_frames(
                self.flow_params, jnp.pad(frames, pads, mode="edge"),
                corr_impl=self.cfg.raft_corr, dtype=flow_dtype,
                n_devices=self.runner.num_devices)
        else:
            total = n * (sp1 - 1)
            if self.cfg.flow_pair_chunk is not None:
                chunk = self.cfg.flow_pair_chunk or None  # 0 → never chunk
            else:
                # auto: the per-pair decoder working set scales with the
                # /64 flow grid (PWC's internal geometry, models/pwc.py
                # _grid64); 64 pairs at 256×384 exceeds HBM while 64 at
                # 256² fits (BASELINE.md round-3 note)
                from ..models.pwc import _grid64

                h64, w64 = _grid64(h, w)
                chunk = 16 if total * h64 * w64 > 5_000_000 else None
            flow = pwc_forward_frames(self.flow_params, frames,
                                      corr_impl=self.cfg.pwc_corr,
                                      dtype=flow_dtype,
                                      pair_chunk=chunk,
                                      warp_impl=self.cfg.pwc_warp)
        # flow: (N, S, Hp, Wp, 2)
        x = i3d_preprocess_flow(_center_crop_nhwc(flow, self.crop_size),
                                dtype=self.dtype)
        feats = model.apply({"params": params}, x, features=True)
        if self.cfg.show_pred:
            _, logits = model.apply({"params": params}, x, features=False)
            return feats, logits
        return feats, None

    @functools.cached_property
    def _flow_step(self):
        return self.runner.jit(self._flow_forward)

    @functools.cached_property
    def _flow_step_sharded(self):
        """Frame-sharded flow sandwich (``_flow_frame_sharded`` mode): ONE
        clip per step, its stack_size source frames sharded across the mesh
        plus the replicated final frame. The flow net runs encode-once with
        halo-exchanged pair boundaries; the I3D conv stack consumes the
        sharded flow under GSPMD (XLA partitions or gathers as profitable —
        the flow net dominates the sandwich either way)."""
        model = self.i3d["flow"]
        flow_type = self.flow_type
        flow_params = self.flow_params
        with_pred = self.cfg.show_pred
        dtype = self.dtype
        flow_dtype = (jnp.bfloat16 if self.cfg.flow_dtype == "bfloat16"
                      else jnp.float32)
        raft_corr = self.cfg.raft_corr
        pwc_corr = self.cfg.pwc_corr
        pwc_warp = self.cfg.pwc_warp
        crop = self.crop_size
        pre_crop = self.pre_crop_size
        device_preproc = self._device_preproc
        mesh = self.runner.mesh

        def step(params, frames_u8, last_u8):
            # frames_u8: (S, H, W, 3) uint8 sharded on the frame axis;
            # last_u8: (1, H, W, 3) replicated — together one (S+1)-frame stack
            if device_preproc:
                # raw decoded frames in: per-frame resize shards trivially
                # along the frame axis (no cross-frame support)
                frames_u8 = device_edge_resize_hwc(frames_u8, pre_crop)
                last_u8 = device_edge_resize_hwc(last_u8, pre_crop)
            s, h, w, _c = frames_u8.shape
            frames = frames_u8.astype(jnp.float32)
            last = last_u8.astype(jnp.float32)
            if flow_type == "raft":
                # replicate-pad to /8 and, like the reference, never unpad:
                # the 224 center crop below runs on the padded flow
                ph, pw = (8 - h % 8) % 8, (8 - w % 8) % 8
                pads = ((0, 0), (ph // 2, ph - ph // 2),
                        (pw // 2, pw - pw // 2), (0, 0))
                flow = raft_forward_frames_sharded(
                    flow_params, jnp.pad(frames, pads, mode="edge"),
                    jnp.pad(last, pads, mode="edge"), mesh,
                    corr_impl=raft_corr, dtype=flow_dtype)
            else:
                # per-shard pair count is stack_size/D — already a bounded
                # decoder batch, so --flow_pair_chunk does not apply here
                flow = pwc_forward_frames_sharded(
                    flow_params, frames, last, mesh,
                    corr_impl=pwc_corr, dtype=flow_dtype, warp_impl=pwc_warp)
            # flow: (S, Hp, Wp, 2) sharded on the pair axis → one clip
            x = i3d_preprocess_flow(_center_crop_nhwc(flow[None], crop),
                                    dtype=dtype)
            feats = model.apply({"params": params}, x, features=True)
            if with_pred:
                _, logits = model.apply({"params": params}, x, features=False)
                return feats, logits
            return feats, None

        return self.runner.jit(step, n_batch_args=1, n_replicated_args=1)

    # --- pipeline -----------------------------------------------------------

    def _host_transform(self, rgb: np.ndarray) -> np.ndarray:
        if self._device_preproc:
            return rgb  # ship the raw decoded frame; the stream bodies resize
        return pil_edge_resize(rgb, self.pre_crop_size)

    def pack_spec(self):
        """Corpus-packing seam for every stream mix: slots are
        ``(stack_size + 1, H, W, 3)`` resized stacks — or RAW decoded stacks
        under ``--device_preproc``, where the resize runs inside the stream
        bodies — shape-keyed per decoded
        geometry (the 256-edge resize keys queues by aspect ratio; the
        bucket-planning flow extractors bound geometry counts — here distinct
        aspect ratios simply fill distinct queues and the anti-starvation
        flush keeps rare ones from stranding). Flow and two-stream jobs pack
        too: a sandwich *stack* is a self-contained slot (each stack's flow
        is computed inside it by the same jitted ``_flow_step`` the per-video
        loop runs), and two-stream steps feed one device batch to both
        streams, stacking the per-stream features along a new axis that
        ``finalize`` splits back into output keys.

        Fallbacks: ``--show_pred`` (per-batch prints assume video order) and
        the single-clip frame-sharded flow sandwich (one clip IS the device
        batch — there is nothing to co-pack)."""
        if self.cfg.show_pred or self._flow_frame_sharded:
            return None
        from ..parallel.packer import PackSpec

        streams = self.streams

        def open_clips(path):
            meta, frames_iter = self._open_video(path)
            info = {"fps": meta.fps, "timestamps_ms": []}

            def clips():
                stack: List[np.ndarray] = []
                for rgb, pos in self._timed_frames(frames_iter):
                    stack.append(rgb)
                    if len(stack) - 1 == self.stack_size:
                        info["timestamps_ms"].append(pos)
                        yield np.stack(stack)  # (S+1, H, W, 3) uint8
                        stack = stack[self.step_size :]
                # trailing partial stack dropped, as in the reference (:216-219)

            return info, clips()

        def step(stacks_u8):
            # _put attributes dispatch time + staged bytes to the 'transfer'
            # stage; the packer commits the staged buffer after the step
            dev = self._put(stacks_u8)
            feats = []
            for s in streams:
                stream_step = self._rgb_step if s == "rgb" else self._flow_step
                f, _logits = stream_step(self.i3d_params[s], dev)
                feats.append(f)
            # (N, n_streams, 1024): one fetchable array per batch; the
            # per-stream split happens on host in finalize
            return jnp.stack(feats, axis=1)

        def finalize(path, rows, info):
            out = {s: np.ascontiguousarray(rows[:, k])
                   for k, s in enumerate(streams)}
            out["fps"] = np.array(info["fps"])
            out["timestamps_ms"] = np.array(info["timestamps_ms"])
            return out

        return PackSpec(batch_size=self.clips_per_batch,
                        empty_row_shape=(len(streams), 1024),
                        open_clips=open_clips, step=step, finalize=finalize,
                        **self._paged_fields(self._composite_forward,
                                             self.i3d_params,
                                             self.clips_per_batch))

    def _composite_forward(self, params, stacks_u8):
        # paged composite: every configured stream's un-jitted body over one
        # page, compiled as ONE program by jit_paged — same (N, n_streams,
        # 1024) row layout the bucketed step fetches. A method (not a
        # pack_spec-local closure) so _paged_fields' program cache can key it
        # across pack_spec() calls.
        feats = []
        for s in self.streams:
            body = self._rgb_forward if s == "rgb" else self._flow_forward
            f, _logits = body(params[s], stacks_u8)
            feats.append(f)
        return jnp.stack(feats, axis=1)

    def extract(self, video_path: str) -> Dict[str, np.ndarray]:
        meta, frames_iter = self._open_video(video_path)
        feats_dict: Dict[str, list] = {s: [] for s in self.streams}
        timestamps_ms: List[float] = []
        valid_counts: List[int] = []

        if self._flow_frame_sharded:
            # this mode forwards (frames, last) VIEW tuples of the batch to
            # device_put — the ring cannot track views, so a recycled buffer
            # could be rewritten mid-transfer; keep fresh per-batch arrays
            # (one single-clip stack per step, a small allocation)
            def stage(rows, total=None):
                arr = np.stack(rows)
                return pad_batch(arr, total) if total is not None else arr
        else:
            stage = self._stage_rows

        def stack_batches():
            # clip batches land in reusable staging-ring buffers (uint8 on
            # the wire; the prefetcher's commit hook guards each buffer
            # until its device_put resolves) instead of a fresh np.stack +
            # pad_batch allocation per batch
            stack: List[np.ndarray] = []
            batch: List[np.ndarray] = []
            for rgb, pos in self._timed_frames(frames_iter):
                stack.append(rgb)
                if len(stack) - 1 == self.stack_size:
                    batch.append(np.stack(stack))  # (S+1, H, W, 3) uint8
                    timestamps_ms.append(pos)
                    stack = stack[self.step_size :]
                    if len(batch) == self.clips_per_batch:
                        valid_counts.append(len(batch))
                        yield stage(batch)
                        batch = []
            if batch:  # partial clip batch: zero-pad, rows trimmed after the step
                valid_counts.append(len(batch))
                yield stage(batch, self.clips_per_batch)
            # trailing partial *stack* dropped, as in the reference (:216-219)

        if self._flow_frame_sharded:
            # one clip per step: split each (1, S+1, H, W, 3) stack into its S
            # source frames (sharded on the frame axis) + the final frame
            # (replicated) so the encode-once flow step spans the mesh
            def host_batches():
                for batch in stack_batches():
                    yield batch[0, :-1], batch[0, -1:]

            sharding = (self.runner.batch_sharding, self.runner.replicated)
        else:
            host_batches = stack_batches
            sharding = self.runner.batch_sharding

        # host decode/stacking of batch k+1 overlaps device compute of batch k
        for i, dev_batch in enumerate(
            prefetch_to_device(
                host_batches(),
                sharding=sharding,
                depth=self.cfg.prefetch_depth,
                clock=self.clock,
                # commit is a no-op for the frame-sharded mode's view tuples
                # (their backing ring buffer is guarded per put through the
                # prefetcher only in standard mode)
                commit=self._staging.commit,
            )
        ):
            valid = valid_counts[i]
            for stream in self.streams:
                if stream == "flow" and self._flow_frame_sharded:
                    feats, logits = self._flow_step_sharded(
                        self.i3d_params["flow"], *dev_batch)
                else:
                    step = self._rgb_step if stream == "rgb" else self._flow_step
                    feats, logits = step(self.i3d_params[stream], dev_batch)
                # stays on device; one host fetch per stream per video
                feats_dict[stream].append(feats[:valid])
                self._throttle(feats_dict[stream])
                if logits is not None:
                    logits = self._wait(logits)[:valid]
                    for row, logit in enumerate(logits):
                        n_stack = i * self.clips_per_batch + row
                        print(f"{video_path} @ stack {n_stack} ({stream} stream)")
                        show_predictions_on_dataset(logit[None], "kinetics")

        out = {
            s: (self._wait(jnp.concatenate(v, axis=0)) if v else np.zeros((0, 1024), np.float32))
            for s, v in feats_dict.items()
        }
        out["fps"] = np.array(meta.fps)
        out["timestamps_ms"] = np.array(timestamps_ms)
        return out
