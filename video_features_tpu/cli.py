"""Reference-compatible command line (``/root/reference/main.py:52-84`` flag surface).

``--device_ids`` (CUDA ordinals) is accepted for drop-in compatibility but maps to the
TPU runtime's device count; new TPU-specific flags are added under the same parser.
"""

from __future__ import annotations

import argparse
from typing import Optional, Sequence

from .config import (
    FEATURE_TYPES,
    FLOW_TYPES,
    ON_EXTRACTION,
    STREAMS,
    ExtractionConfig,
    config_from_namespace,
)


def build_parser() -> argparse.ArgumentParser:
    parser = argparse.ArgumentParser(description="Extract Features (TPU-native)")
    parser.add_argument("--feature_type", required=True, choices=list(FEATURE_TYPES))
    parser.add_argument("--video_paths", nargs="+", help="space-separated paths to videos")
    parser.add_argument("--file_with_video_paths", help=".txt file where each line is a path")
    parser.add_argument("--device_ids", type=int, nargs="+",
                        help="compat shim: length = number of TPU devices to use")
    parser.add_argument("--tmp_path", default="./tmp",
                        help="folder for temporary files (re-encoded videos, wav files)")
    parser.add_argument("--keep_tmp_files", action="store_true", default=False,
                        help="keep temp files after extraction (vggish and i3d)")
    parser.add_argument("--on_extraction", default="print", choices=list(ON_EXTRACTION),
                        help="what to do once the stack is extracted")
    parser.add_argument("--output_path", default="./output", help="where to store results if saved")
    parser.add_argument("--extraction_fps", type=int, help="do not specify for original video fps")
    parser.add_argument("--stack_size", type=int, help="feature time span in frames")
    parser.add_argument("--step_size", type=int, help="feature step size in frames")
    parser.add_argument("--streams", nargs="+", choices=list(STREAMS),
                        help="streams to use for i3d; both if not specified")
    parser.add_argument("--flow_type", choices=list(FLOW_TYPES), default="pwc",
                        help="flow net used in i3d. PWC is faster, RAFT more accurate.")
    parser.add_argument("--batch_size", type=int, default=1,
                        help="batch size for frame-wise / frame-pair extractors")
    parser.add_argument("--resize_to_larger_edge", dest="resize_to_smaller_edge",
                        action="store_false", default=True,
                        help="resize the larger side to --side_size instead of the smaller")
    parser.add_argument("--side_size", type=int,
                        help="if specified, inputs are edge-resized to this size (raft/pwc)")
    parser.add_argument("--show_pred", action="store_true", default=False,
                        help="print model predictions (kinetics/imagenet top-5)")

    # TPU-native flags (no reference equivalent)
    parser.add_argument("--dtype", default="float32", choices=["float32", "bfloat16"],
                        help="device compute dtype; float32 gives reference parity")
    parser.add_argument("--clips_per_batch", type=int, default=1,
                        help="clips per jitted device step (MXU utilization)")
    parser.add_argument("--num_devices", type=int, default=None,
                        help="devices in the data-parallel mesh (default: all local)")
    parser.add_argument("--resume", action="store_true", default=False,
                        help="skip videos recorded in the output done-manifest")
    parser.add_argument("--flow_dtype", default="float32",
                        choices=["float32", "bfloat16"],
                        help="RAFT/PWC conv + correlation storage dtype; "
                             "correlation ACCUMULATION and coordinate math stay "
                             "fp32 either way (float32 = reference parity; "
                             "measured bf16 drift in tests/test_flow_bf16.py)")
    parser.add_argument("--raft_corr",
                        choices=["auto", "volume", "volume_gather", "on_demand",
                                 "on_demand_matmul"],
                        default="auto",
                        help="RAFT correlation: auto (default) = materialized "
                             "pyramid with MXU matmul lookup unless the volume "
                             "would outgrow HBM for the frame size, then "
                             "on_demand (the alt_cuda_corr equivalent, O(H*W) "
                             "memory; VFT_RAFT_ON_DEMAND_IMPL=matmul opts into "
                             "the MXU volume remat pending a 1080p TPU sweep); "
                             "or force volume / volume_gather / on_demand / "
                             "on_demand_matmul")
    parser.add_argument("--pwc_corr", choices=["auto", "xla", "pallas"],
                        default="auto",
                        help="PWC cost-volume implementation: auto picks the "
                             "Pallas tile kernel where its VMEM gate admits "
                             "the shape, else the fused XLA formulation")
    parser.add_argument("--pwc_warp", choices=["auto", "gather", "onehot"],
                        default="auto",
                        help="PWC backward-warp lowering: gather corner taps "
                             "or one-hot MXU selector matmuls (covers the "
                             "levels the Mosaic cliff bars from the fused "
                             "kernel); auto defers to VFT_WARP_IMPL")
    parser.add_argument("--flow_pair_chunk", type=int, default=None,
                        help="i3d flow sandwich: decode PWC pairs in sub-batches "
                             "of this size to bound HBM (default: auto; 0 = never; "
                             "PWC only — the RAFT sandwich bounds memory via "
                             "--raft_corr auto instead)")
    parser.add_argument("--float32_wire", action="store_true", default=False,
                        help="flow models: stage frame windows as float32 on "
                             "the host (the pre-uint8 wire format) — 4x the "
                             "host->device bytes for byte-identical outputs; "
                             "A/B escape hatch and the bench baseline "
                             "(docs/performance.md ingest fast path)")
    parser.add_argument("--device_resize", action="store_true", default=False,
                        help="resnet50: ship RAW decoded frames and run the "
                             "edge resize + center crop inside the jitted "
                             "step (jax.image.resize) — removes the host PIL "
                             "resize cost; NOT bit-identical to the PIL path "
                             "(documented tolerance, docs/performance.md); "
                             "off = bit-parity. --device_preproc is the "
                             "every-model generalization")
    parser.add_argument("--device_preproc", action="store_true", default=False,
                        help="move every remaining host-side preprocess "
                             "inside the jitted step (generalizes "
                             "--device_resize to all feature types): "
                             "resnet50/i3d resize on device (documented "
                             "tolerance), raft/pwc ship raw frames and "
                             "replicate-pad on device (byte-exact), vggish "
                             "ships raw PCM and computes the log-mel on "
                             "device (<=2e-5 vs the numpy oracle); r21d has "
                             "been fully device-side since its port. Frees "
                             "the decode pool from per-frame PIL/numpy work "
                             "at more host->device bytes per video "
                             "(docs/performance.md ingest fast path)")
    parser.add_argument("--transfer_dtype", default="float32",
                        choices=["float32", "float16", "bfloat16"],
                        help="raft/pwc: cast dense flow to this on device "
                             "before the host fetch (halves/quarters D2H "
                             "bytes; host upcasts, .npy outputs stay fp32; "
                             "float16 quantizes <=0.01 px for |flow|<=32)")
    parser.add_argument("--i3d_pre_crop_size", type=int, default=256,
                        help="i3d smaller-edge resize target (reference: 256); "
                             "override only for CI/dry runs — non-default values "
                             "change features")
    parser.add_argument("--i3d_crop_size", type=int, default=224,
                        help="i3d center-crop size (reference: 224); override "
                             "only for CI/dry runs — non-default values change "
                             "features")
    parser.add_argument("--decode_workers", type=int, default=1,
                        help="background threads decoding upcoming videos while the "
                             "device computes (frame-stream models); 1 = inline; "
                             "0 = auto (start from a CPU-derived size; the "
                             "--serve daemon then grows/shrinks the pool live "
                             "from the measured occupancy vs decode signal)")
    parser.add_argument("--decode_segments", type=int, default=0,
                        help="segmented intra-video decode: split one video "
                             "into seek-aligned segments decoded concurrently "
                             "by the pool and streamed back in order, "
                             "byte-identical to sequential decode; 0 = auto "
                             "(segment long videos when the pool has idle "
                             "permits), 1 = off, N caps the split; needs "
                             "--decode_workers > 1")
    parser.add_argument("--segment_seek", default="auto",
                        choices=["auto", "ffmpeg", "cv2"],
                        help="seek backend landing a segment on its start "
                             "frame: auto = verified cv2 CAP_PROP_POS_FRAMES "
                             "seek with ffmpeg -ss fast-seek fallback for "
                             "resampled streams cv2 cannot land on; "
                             "cv2/ffmpeg force a backend")
    parser.add_argument("--pack_corpus", action="store_true", default=False,
                        help="corpus-level clip packing: fill every device "
                             "batch with clips from however many videos are "
                             "ready instead of zero-padding each video's tail "
                             "batch. Every feature type packs (RGB stacks, "
                             "flow frame-pairs, i3d sandwich stacks, vggish "
                             "log-mel slabs; flow models bucket mixed "
                             "geometries via --pack_buckets, other models "
                             "queue per decoded shape) — the per-video "
                             "fallbacks are "
                             "--show_pred and the single-clip frame-sharded "
                             "flow sandwich, each with a printed notice. "
                             "Per-video fault attribution and resume "
                             "preserved; features are byte-identical to the "
                             "per-video loop except where a merged flow "
                             "bucket pads frames (--pack_buckets border "
                             "caveat) — docs/performance.md")
    parser.add_argument("--pack_buckets", type=int, default=4,
                        help="--pack_corpus flow models: cluster the corpus's "
                             "probed geometries into at most this many padded "
                             "shape buckets (one compiled program each) "
                             "before decode starts; merged buckets carry "
                             "--shape_bucket's border-perturbation caveat")
    parser.add_argument("--no_paged_batching", dest="paged_batching",
                        action="store_false", default=True,
                        help="disable ragged paged dispatch under "
                             "--pack_corpus: buckets fall back to batch_size "
                             "padded batches (one in flight) instead of "
                             "fixed-size pages with an int32 row table and "
                             "a donated table buffer. Paged dispatch is on "
                             "by default for the slot-shaped paths "
                             "(resnet50 — including raw-wire "
                             "--device_resize/--device_preproc frames, "
                             "r21d, i3d stacks, vggish), with "
                             "mixed-geometry slots paging per-queue under "
                             "one compiled family; the collate models "
                             "(raft/pwc) always dispatch bucketed — "
                             "docs/performance.md")
    parser.add_argument("--pages_in_flight", type=int, default=2,
                        help="paged dispatch: in-flight pages per bucket "
                             "(page_rows = ceil(batch budget / depth), so "
                             "total in-flight rows match one bucketed "
                             "batch; >= 2 overlaps host refill with device "
                             "compute)")
    parser.add_argument("--pack_flush_age", type=int, default=8,
                        help="--pack_corpus anti-starvation flush: dispatch a "
                             "bucket's partial queue once this many videos "
                             "finished while it waited, so a rare geometry "
                             "cannot strand its videos until corpus end "
                             "(0 = flush only at corpus end)")
    parser.add_argument("--shape_bucket", type=int, default=None,
                        help="flow models: replicate-pad frames to multiples of this "
                             "size (multiple of 8) so a mixed-resolution corpus "
                             "compiles one program per bucket, not per geometry; "
                             "off = reference-exact /8 padding only")
    parser.add_argument("--use_ffmpeg", choices=["auto", "always", "never"],
                        default="auto",
                        help="--extraction_fps backend: ffmpeg re-encode when "
                             "installed (auto; reference parity) or the native "
                             "vf_fps-semantics sampler (never; host-independent)")
    parser.add_argument("--vggish_postprocess", action="store_true", default=False,
                        help="apply the AudioSet PCA-whiten + uint8 quantize "
                             "postprocessor to VGGish embeddings (vendored params; "
                             "the reference loads but never applies it)")
    # Reliability flags (docs/reliability.md)
    parser.add_argument("--retries", type=int, default=2,
                        help="re-attempts after a TRANSIENT per-video failure "
                             "(FfmpegError/DeviceError/OutputError); permanent "
                             "classes (DecodeError, watchdog timeouts) never retry")
    parser.add_argument("--retry_backoff", type=float, default=0.5,
                        help="first retry delay in seconds; doubles per retry "
                             "(capped at 30s)")
    parser.add_argument("--video_timeout", type=float, default=None,
                        help="per-video watchdog: cancel any video whose attempt "
                             "exceeds this many seconds and record it as "
                             "VideoTimeoutError (default: no timeout)")
    parser.add_argument("--max_failures", type=int, default=None,
                        help="circuit breaker: abort the run (exit code 2) once "
                             "more than this many videos have terminally failed "
                             "(0 = abort on first failure; default: never)")
    parser.add_argument("--retry_failed", action="store_true", default=False,
                        help="reprocess exactly the videos in the failure manifest "
                             "(<output>/<feature_type>/.failed_manifest.jsonl) "
                             "instead of --video_paths/--file_with_video_paths")
    parser.add_argument("--compilation_cache", default=None,
                        help="persistent XLA compilation cache directory: "
                             "compiles longer than ~1s are cached so reruns "
                             "and restarts skip straight to execution "
                             "(docs/performance.md)")
    parser.add_argument("--precompile", action="store_true", default=False,
                        help="flow models: warm the device program for each "
                             "video's (bucketed) geometry in a background "
                             "thread while the host decodes, overlapping "
                             "mixed-resolution recompiles with decode "
                             "(combine with --shape_bucket/--compilation_cache)")
    parser.add_argument("--sync_writer", dest="async_writer",
                        action="store_false", default=True,
                        help="disable the async output writer and serialize "
                             ".npy writes inside the per-video loop (the "
                             "default writer thread overlaps serialization "
                             "with the next video's compute, preserving "
                             "atomic writes and write-before-done ordering)")
    # Serving flags (--serve daemon, docs/serving.md)
    parser.add_argument("--serve", action="store_true", default=False,
                        help="run the always-on extraction service instead "
                             "of the batch loop: watch --spool_dir for "
                             "per-tenant request files (+ a local-socket "
                             "API), schedule videos weighted-fair + deadline "
                             "across tenants, and keep the corpus packer's "
                             "slot queues warm across requests; SIGTERM "
                             "drains, SIGHUP reloads (docs/serving.md)")
    parser.add_argument("--spool_dir", default=None,
                        help="--serve: watched request directory — tenants "
                             "drop <request_id>.json files here; "
                             "tenants.json in the same directory sets "
                             "per-tenant weights/quotas")
    parser.add_argument("--socket_path", default=None,
                        help="--serve: Unix socket for the submit/status/"
                             "stats/drain/reload API (default: "
                             "<spool_dir>/control.sock; 'none' disables)")
    parser.add_argument("--notify_dir", default=None,
                        help="--serve: directory for per-request "
                             "<request_id>.result.json completion records "
                             "(default: <spool_dir>/results)")
    parser.add_argument("--tenant_quota", type=int, default=64,
                        help="--serve: default per-tenant pending-video "
                             "quota; submissions past it are rejected at "
                             "admission (tenants.json overrides per tenant)")
    parser.add_argument("--tenant_max_failures", type=int, default=None,
                        help="--serve: per-tenant circuit breaker — once "
                             "more than this many of a tenant's videos "
                             "terminally failed, fail its queue fast and "
                             "reject its submissions until SIGHUP reload "
                             "(0 = trip on first failure; default: never)")
    parser.add_argument("--idle_flush_sec", type=float, default=0.5,
                        help="--serve: with the ingest queue idle, wait this "
                             "long before pad-flushing partial slot queues "
                             "so in-flight requests complete (latency over "
                             "occupancy when there is nothing to pack with)")
    parser.add_argument("--spool_poll_sec", type=float, default=0.25,
                        help="--serve: spool directory poll interval")
    parser.add_argument("--serve_models", nargs="+",
                        choices=list(FEATURE_TYPES), default=None,
                        help="--serve: co-load these additional feature "
                             "types into the SAME daemon and mesh — "
                             "requests pick one via their 'feature_type' "
                             "key (--feature_type stays the default) and "
                             "the packer interleaves dispatch round-robin "
                             "across models, so mixed traffic never drains "
                             "the device. Each model keeps its own output "
                             "subtree, manifests, reference stack/step "
                             "defaults, and cache fingerprint "
                             "(docs/serving.md)")
    # Serving durability (serve/wal.py, docs/serving.md "Crash recovery")
    parser.add_argument("--wal_path", default=None,
                        help="--serve: write-ahead admission log — every "
                             "accepted request is on disk before its submit "
                             "is acknowledged, and a crashed daemon replays "
                             "unresolved entries at the next start (default: "
                             "<spool_dir>/admission.wal; 'none' disables "
                             "durable admission)")
    parser.add_argument("--wal_fsync_sec", type=float, default=0.0,
                        help="--serve: WAL group-commit window — admissions "
                             "within this many seconds share one batched "
                             "fsync (default 0: fsync every record before "
                             "acknowledging; ~0.05 recommended under high "
                             "submit rates)")
    parser.add_argument("--no_recover", dest="recover", action="store_false",
                        default=True,
                        help="--serve: do NOT replay unresolved WAL "
                             "admissions at startup — they are resolved "
                             "failed and dropped (default: replay, deduped "
                             "against published results and done-manifests, "
                             "with original admission seqs and deadlines)")
    parser.add_argument("--healthz_stale_sec", type=float, default=10.0,
                        help="--serve: healthz flags the daemon `stale` once "
                             "the serving loop has not stepped for this many "
                             "seconds (wedge, or a legitimately long "
                             "first-traffic compile)")
    parser.add_argument("--spool_retain", action="store_true", default=False,
                        help="--serve: keep claimed <id>.json.accepted spool "
                             "files after their result record publishes "
                             "(debugging; default removes them)")
    parser.add_argument("--step_watchdog_sec", type=float, default=None,
                        help="--serve: hung-step watchdog — when the serving "
                             "loop stalls past this many seconds, fail the "
                             "in-flight videos transiently so they requeue "
                             "instead of waiting out a wedged device step "
                             "(set well above the worst expected compile "
                             "time; default: off)")
    # Feature cache (docs/caching.md)
    parser.add_argument("--cache_dir", default=None,
                        help="content-addressed feature cache: "
                             "sha256(container bytes) x model-config "
                             "fingerprint -> finished features. A hit costs "
                             "zero decode and zero device steps and still "
                             "writes outputs + a done-manifest entry "
                             "(--resume composes); the --serve daemon also "
                             "coalesces in-flight identical requests so N "
                             "tenants submitting the same video run one "
                             "extraction (docs/caching.md)")
    parser.add_argument("--cache_max_bytes", type=int, default=None,
                        help="--cache_dir byte cap: publishing past it "
                             "evicts the least-recently-hit entries "
                             "(default: unbounded)")
    parser.add_argument("--profile_dir", default=None,
                        help="write a jax.profiler trace here and print per-video "
                             "stage timing (decode vs device wait)")
    parser.add_argument("--telemetry_dir", default=None,
                        help="write a structured span/event journal "
                             "(<dir>/events.jsonl) of every request/video "
                             "lifecycle — queued, popped, decode, device, "
                             "done/failed, cache hits, breaker trips — via a "
                             "bounded writer thread that never blocks the "
                             "hot path; export a Chrome/Perfetto trace with "
                             "`python -m video_features_tpu.obs.export "
                             "<dir>/events.jsonl` (docs/observability.md)")
    parser.add_argument("--matmul_precision", default=None,
                        choices=["default", "high", "highest"],
                        help="TPU fp32 matmul/conv precision; 'highest' for "
                             "bit-parity with the torch reference")
    return parser


def parse_args(argv: Optional[Sequence[str]] = None) -> ExtractionConfig:
    ns = build_parser().parse_args(argv)
    if ns.device_ids is not None and ns.num_devices is None:
        ns.num_devices = len(ns.device_ids)
    if ns.show_pred:
        # reference forces a single device for prediction printing (utils/utils.py:95-97)
        print("You want to see predictions. So, I will use only one device.")
        ns.num_devices = 1
        if ns.feature_type == "vggish":
            print("Showing class predictions is not implemented for VGGish")
    if ns.on_extraction == "save_numpy":
        print(f"Saving features to {ns.output_path}")
    if ns.keep_tmp_files:
        print(f"Keeping temp files in {ns.tmp_path}")
    return config_from_namespace(ns)
