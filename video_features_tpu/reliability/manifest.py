"""Failure manifest: a durable, append-only record of what failed and why.

Lives beside the done-manifest (``io/output.py``) as
``.failed_manifest.jsonl`` in the per-feature output directory. Each line is
one terminal failure: video path, taxonomy class, transient tag, attempt
count, message, and a traceback digest that groups identical failure sites
across a corpus. ``--retry_failed`` consumes it (:func:`take_failed_videos`);
operators grep it to answer "what died, and was it our fault?" without
scraping logs.
"""

from __future__ import annotations

import json
import os
import sys
from typing import Dict, List, Tuple

from .errors import classify, traceback_digest

FAILED_MANIFEST_NAME = ".failed_manifest.jsonl"


def read_jsonl(path: str) -> Tuple[List[dict], int]:
    """Tolerantly read a JSONL manifest: (dict records, corrupt line count).

    Shared by the done- and failure-manifests: blank lines are ignored,
    undecodable or non-dict lines are counted (callers warn — a dropped line
    is a video whose state the operator no longer knows).
    """
    records: List[dict] = []
    corrupt = 0
    if not os.path.exists(path):
        return records, corrupt
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except json.JSONDecodeError:
                corrupt += 1
                continue
            if not isinstance(rec, dict):
                corrupt += 1
                continue
            records.append(rec)
    return records, corrupt


def failed_manifest_path(output_dir: str) -> str:
    return os.path.join(output_dir, FAILED_MANIFEST_NAME)


def record_failure(
    output_dir: str, video_path: str, exc: BaseException, attempts: int = 1
) -> dict:
    """Append one failure record; returns the record written."""
    error_class, transient = classify(exc)
    record = {
        "video": os.path.abspath(video_path),
        "error_class": error_class,
        "transient": transient,
        "attempts": int(attempts),
        "message": str(exc)[:500],
        "traceback_digest": traceback_digest(exc),
    }
    os.makedirs(output_dir, exist_ok=True)
    with open(failed_manifest_path(output_dir), "a") as f:
        f.write(json.dumps(record) + "\n")
    return record


def load_failures(output_dir: str) -> Dict[str, dict]:
    """{abs video path: last failure record}; warns on corrupt lines.

    The last record per video wins — a video that failed, was retried by a
    later run, and failed again appears once with its latest classification.
    """
    out: Dict[str, dict] = {}
    path = failed_manifest_path(output_dir)
    records, corrupt = read_jsonl(path)
    for record in records:
        if "video" in record:
            out[record["video"]] = record
        else:
            corrupt += 1
    if corrupt:
        print(
            f"warning: ignored {corrupt} corrupt line(s) in {path}; "
            "those failures are invisible to --retry_failed",
            file=sys.stderr,
        )
    return out


def prune_failures(output_dir: str, videos) -> None:
    """Rewrite the manifest without records for ``videos`` (abs or raw paths).

    The run loop prunes the videos that *succeeded*, in one batch at run exit
    (never the whole manifest up front): an interrupted ``--retry_failed`` run
    then loses no records — the not-yet-attempted tail stays in the manifest
    for the next run. Single-host only (callers guard): this read-modify-
    replace would race concurrent ``record_failure`` appends from other hosts.
    If the last record vanishes the manifest file is removed entirely, so "no
    failure manifest" stays synonymous with "nothing failed".
    """
    path = failed_manifest_path(output_dir)
    if not os.path.exists(path):
        return
    drop = {os.path.abspath(v) for v in videos}
    keep = [r for v, r in load_failures(output_dir).items() if v not in drop]
    if not keep:
        os.remove(path)
        return
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        for record in keep:
            f.write(json.dumps(record) + "\n")
    os.replace(tmp, path)
