"""Fault-tolerance subsystem: error taxonomy, retry, watchdog, failure manifest.

The reference's only robustness feature is a per-video ``except Exception: print``
barrier (``extract_i3d.py:107-117``). At fleet scale the failure modes the systems
papers treat as first-class (corrupt containers, wedged subprocesses, partial
writes, device faults — PAPERS.md: "TensorFlow: A system for large-scale machine
learning", "Podracer architectures") need classification, bounded retry,
cancellation, and a durable record. This package provides the pieces; the io
layer raises the taxonomy, :mod:`..extractors.base` runs the barrier, and
``reliability/faults.py`` injects failures so tests can prove the loop end to end.
"""

from .breaker import TenantBreaker, TenantBreakerOpen
from .errors import (
    CacheError,
    CircuitBreakerTripped,
    DecodeError,
    DeviceError,
    ExtractionError,
    FfmpegError,
    OutputError,
    VideoTimeoutError,
    classify,
    traceback_digest,
)
from .faults import fault_point, reset_faults
from .manifest import (
    FAILED_MANIFEST_NAME,
    failed_manifest_path,
    load_failures,
    prune_failures,
    record_failure,
)
from .retry import RetryPolicy, retry_call
from .watchdog import run_with_timeout

__all__ = [
    "CacheError",
    "CircuitBreakerTripped",
    "TenantBreaker",
    "TenantBreakerOpen",
    "DecodeError",
    "DeviceError",
    "ExtractionError",
    "FfmpegError",
    "OutputError",
    "VideoTimeoutError",
    "classify",
    "traceback_digest",
    "fault_point",
    "reset_faults",
    "FAILED_MANIFEST_NAME",
    "failed_manifest_path",
    "load_failures",
    "prune_failures",
    "record_failure",
    "RetryPolicy",
    "retry_call",
    "run_with_timeout",
]
