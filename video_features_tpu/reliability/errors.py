"""Error taxonomy for the extraction pipeline.

Every failure the io layer can produce is one of four classes, each tagged
transient (worth retrying: the same input may succeed on a second attempt) or
permanent (retry is wasted work: the input itself is bad). The per-video fault
barrier (``extractors/base.py``) keys retry and manifest decisions off the tags
instead of guessing from exception types.

Classes:

- :class:`DecodeError` — unopenable/corrupt container, mid-stream decode
  failure. Permanent: the bytes on disk will not improve.
- :class:`FfmpegError` — ffmpeg subprocess failed (nonzero exit, missing
  output, killed). Transient: subprocesses die for environmental reasons
  (OOM killer, tmp-dir pressure) that clear up.
- :class:`DeviceError` — accelerator runtime failure. Transient: device
  restarts and preemptions heal.
- :class:`OutputError` — writing features or manifests failed. Transient:
  disk pressure and NFS hiccups clear up.
- :class:`VideoTimeoutError` — the per-video watchdog cancelled a wedged
  video. Permanent by default: a decode that hangs once usually hangs again,
  and re-running it re-wedges the host for another full timeout.
"""

from __future__ import annotations

import hashlib
import traceback
from typing import Tuple


class ExtractionError(Exception):
    """Base of the taxonomy; ``transient`` is a class-level retry tag."""

    transient: bool = False

    @property
    def error_class(self) -> str:
        return type(self).__name__


class DecodeError(ExtractionError):
    """Corrupt/unopenable container or a failed decode stream."""

    transient = False


class FfmpegError(ExtractionError):
    """ffmpeg subprocess failure (nonzero exit, missing/empty output)."""

    transient = True


class DeviceError(ExtractionError):
    """Accelerator runtime failure (XLA runtime errors map here)."""

    transient = True


class OutputError(ExtractionError):
    """Feature/manifest write failure."""

    transient = True


class VideoTimeoutError(ExtractionError):
    """Per-video watchdog fired; the video was cancelled, not completed."""

    transient = False


class CacheError(ExtractionError):
    """Feature-cache entry unreadable or corrupt (checksum mismatch, torn
    file, broken cache disk). Transient in the taxonomy sense — the content
    is recomputable — and by contract never escapes :mod:`..cache`: the
    store quarantines the entry, reports a miss, and extraction proceeds."""

    transient = True


class CircuitBreakerTripped(Exception):
    """Run-level abort: more failures than ``--max_failures`` allows.

    Deliberately outside the :class:`ExtractionError` taxonomy — it is not a
    per-video fault and must never be swallowed by the per-video barrier.
    """


def classify(exc: BaseException) -> Tuple[str, bool]:
    """(error_class, transient) for any exception the barrier can see.

    Taxonomy members carry their own tags. XLA runtime errors (matched by type
    name — jaxlib's class lives at an unstable import path) are device faults
    and therefore transient. Everything else is an unknown permanent error:
    retrying an exception we cannot classify just repeats the work.
    """
    if isinstance(exc, ExtractionError):
        return exc.error_class, exc.transient
    if type(exc).__name__ == "XlaRuntimeError":
        return DeviceError.__name__, DeviceError.transient
    return type(exc).__name__, False


def traceback_digest(exc: BaseException, length: int = 12) -> str:
    """Short stable digest of an exception's traceback frames.

    Hashes the ``file:line:function`` chain (not the message, which embeds
    per-video paths) so the failure manifest groups identical failure sites
    across thousands of videos.
    """
    frames = traceback.extract_tb(exc.__traceback__)
    sig = "|".join(f"{f.filename}:{f.lineno}:{f.name}" for f in frames)
    if not sig:
        sig = type(exc).__name__
    return hashlib.sha1(sig.encode()).hexdigest()[:length]
