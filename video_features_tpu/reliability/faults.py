"""Fault-injection harness: deterministic failures at declared pipeline sites.

The fault-tolerance claims in this package are only real if tests can crash
the pipeline on demand. Production code calls :func:`fault_point(site, key)`
at the seams where real failures occur; the hook is inert unless the
``VFT_FAULTS`` environment variable names that site. Tests (and chaos drills
on a staging fleet) set the variable; production never does, so the hook cost
is one env read per video, not per frame.

Spec grammar — rules separated by ``;``, fields by ``:``::

    VFT_FAULTS = "site:action[:match[:count]] [; ...]"

- ``site`` — one of the declared sites below.
- ``action`` — ``raise`` (site's default taxonomy error), ``raise_transient``
  / ``raise_permanent`` (force the retry tag), ``hang(SECONDS)`` (sleep,
  simulating a wedged decode — pair with ``--video_timeout``), or ``kill``
  (``os._exit(137)``, simulating SIGKILL mid-operation).
- ``match`` — substring of the key (usually the video path); empty matches all.
- ``count`` — how many times the rule fires before going inert; empty =
  unlimited. ``ffmpeg:raise::1`` fails exactly the first ffmpeg call — the
  canonical transient-then-success retry test.

Declared sites: ``probe`` and ``decode`` (io/video.py), ``decode_segment``
(io/video.py, fires per segment with key ``<path>#seg<index>`` so one poisoned
segment of one video can be targeted), ``ffmpeg``
(io/ffmpeg.py, also guards the segment fast-seek streamer), ``save``
(io/output.py, between tmp-write and atomic rename),
``extract`` (extractors/base.py, wraps the whole per-video attempt),
``pool_worker`` (parallel/pipeline.py decode-worker body), ``device``
(parallel/packer.py, just before a batch's device step dispatches), and the
serving durability seams (docs/reliability.md "Serving chaos seams"):
``wal_append`` (serve/wal.py, before an admission record is written — an
injected OSError here is the ENOSPC degrade drill), ``wal_sync``
(serve/wal.py, after write/flush but before fsync — a ``kill`` here is the
post-accept/pre-sync crash), and ``publish`` (serve/daemon.py, before a
finished request's result record writes — the post-extract/pre-publish
crash).
"""

from __future__ import annotations

import os
import re
import threading
import time
from typing import List, Optional

from .errors import (
    DecodeError,
    DeviceError,
    ExtractionError,
    FfmpegError,
    OutputError,
)

ENV_VAR = "VFT_FAULTS"

_SITE_ERRORS = {
    "probe": DecodeError,
    "decode": DecodeError,
    "decode_segment": DecodeError,
    "pool_worker": DecodeError,
    "ffmpeg": FfmpegError,
    "extract": DeviceError,
    "device": DeviceError,
    "save": OutputError,
    "wal_append": OutputError,
    "wal_sync": OutputError,
    "publish": OutputError,
}


class _Rule:
    __slots__ = ("site", "action", "arg", "match", "remaining")

    def __init__(self, site: str, action: str, arg: float, match: str, count: Optional[int]):
        self.site = site
        self.action = action
        self.arg = arg
        self.match = match
        self.remaining = count  # None = unlimited


def _parse(spec: str) -> List[_Rule]:
    rules = []
    for chunk in spec.split(";"):
        chunk = chunk.strip()
        if not chunk:
            continue
        fields = chunk.split(":")
        if len(fields) < 2:
            raise ValueError(f"{ENV_VAR} rule needs site:action, got {chunk!r}")
        site, action = fields[0].strip(), fields[1].strip()
        match = fields[2].strip() if len(fields) > 2 else ""
        count = int(fields[3]) if len(fields) > 3 and fields[3].strip() else None
        arg = 0.0
        m = re.fullmatch(r"hang\(([\d.]+)\)", action)
        if m:
            action, arg = "hang", float(m.group(1))
        if action not in ("raise", "raise_transient", "raise_permanent", "hang", "kill"):
            raise ValueError(f"unknown fault action {action!r} in {chunk!r}")
        rules.append(_Rule(site, action, arg, match, count))
    return rules


# the parsed-rule cache below is guarded by this module lock (vftlint
# GUARDED_BY: 'faults' lock) — fault_point fires from decode workers, the
# daemon thread, and the run loop concurrently
_lock = threading.Lock()
_cached_spec: Optional[str] = None
_rules: List[_Rule] = []


def reset_faults() -> None:
    """Drop the parsed-rule cache (tests flip ``VFT_FAULTS`` between cases)."""
    global _cached_spec, _rules
    with _lock:
        _cached_spec = None
        _rules = []


def _injected_error(site: str, force_transient: Optional[bool]) -> ExtractionError:
    base = _SITE_ERRORS.get(site, DeviceError)
    if force_transient is None or force_transient == base.transient:
        return base(f"injected fault at site {site!r}")
    cls = type(f"Injected{base.__name__}", (base,), {"transient": force_transient})
    return cls(f"injected fault at site {site!r} (forced transient={force_transient})")


def fault_point(site: str, key: str = "") -> None:
    """Production hook: crash/hang/die here iff ``VFT_FAULTS`` says so."""
    spec = os.environ.get(ENV_VAR, "")
    if not spec:
        return
    global _cached_spec, _rules
    with _lock:
        if spec != _cached_spec:
            _rules = _parse(spec)
            _cached_spec = spec
        fire = None
        for rule in _rules:
            if rule.site != site or rule.match not in key:
                continue
            if rule.remaining is not None:
                if rule.remaining <= 0:
                    continue
                rule.remaining -= 1
            fire = rule
            break
    if fire is None:
        return
    if fire.action == "hang":
        deadline = time.monotonic() + (fire.arg or 3600.0)
        while time.monotonic() < deadline:
            time.sleep(0.05)
        return
    if fire.action == "kill":
        os._exit(137)
    force = {"raise": None, "raise_transient": True, "raise_permanent": False}[fire.action]
    raise _injected_error(site, force)
