"""Per-video watchdog: bound the wall-clock any single video may consume.

A wedged cv2 read or ffmpeg child otherwise stalls the whole host — the fleet
failure mode the large-scale systems papers design out first. Python cannot
kill an arbitrary thread, so the watchdog runs the attempt in a daemon worker
and *abandons* it on timeout: the caller gets a classified
:class:`~.errors.VideoTimeoutError` immediately and moves to the next video,
while the wedged thread either unwinds when its decode-pool slot is released
(the run loop's per-video ``finally`` cancels the stream) or is reclaimed at
process exit. That trade — a leaked thread vs. a hung fleet — is the right one
for batch extraction.
"""

from __future__ import annotations

import threading
from typing import Callable, Optional, TypeVar

from .errors import VideoTimeoutError

T = TypeVar("T")


def run_with_timeout(
    fn: Callable[[], T],
    timeout: Optional[float],
    label: str = "",
    on_timeout: Optional[Callable[[], None]] = None,
) -> T:
    """Run ``fn()`` with a wall-clock bound; ``timeout=None`` runs inline.

    On timeout raises :class:`VideoTimeoutError` (permanent: a video that
    wedges once usually wedges again). Exceptions from ``fn`` propagate with
    their original traceback; KeyboardInterrupt in the waiting thread
    propagates immediately (the abandoned worker is a daemon).

    ``on_timeout`` fires before the raise — the extraction loop passes a
    cancellation event's ``set`` so the abandoned attempt, should it wake up
    later over a partial frame stream, discards its results instead of writing
    truncated features behind a done-manifest record.
    """
    if timeout is None:
        return fn()
    if timeout <= 0:
        raise ValueError("timeout must be > 0 (or None to disable)")

    result: list = []
    error: list = []

    def target() -> None:
        try:
            result.append(fn())
        except BaseException as exc:  # noqa: BLE001 — fault-barrier: handed to the waiter
            error.append(exc)

    t = threading.Thread(target=target, daemon=True, name=f"watchdog:{label}")
    t.start()
    t.join(timeout)
    if t.is_alive():
        if on_timeout is not None:
            on_timeout()
        raise VideoTimeoutError(
            f"{label or 'video'}: exceeded --video_timeout {timeout:g}s; "
            "cancelled (decode stream released, worker thread abandoned)"
        )
    if error:
        raise error[0]
    return result[0]
