"""Per-tenant circuit breaker for the always-on extraction service.

The batch CLI's ``--max_failures`` breaker (:class:`.errors.CircuitBreakerTripped`)
aborts the *run* — correct for a finite corpus with one owner, wrong for a
daemon multiplexing tenants: one tenant uploading a directory of corrupt
containers must not take the service down for everyone else. This breaker
scopes the same idea to a tenant: once MORE THAN ``max_failures`` of a
tenant's videos have terminally failed, that tenant's breaker opens — the
daemon fails its queued videos fast (classified, manifested) and rejects its
new submissions — while every other tenant keeps flowing. A SIGHUP reload
(or an explicit :meth:`reset`) closes breakers again, the operator's
"cause fixed, let them back in" lever.

Single-threaded by design: the daemon's scheduler loop owns all mutation
(submission-side reads happen under the ingest queue's lock, which the
daemon also holds while recording failures).
"""

from __future__ import annotations

from typing import Dict, Iterable, Optional


class TenantBreakerOpen(Exception):
    """A tenant's breaker is open; raised at admission, never per-video.

    Outside the :class:`.errors.ExtractionError` taxonomy for the same
    reason ``CircuitBreakerTripped`` is: it is a policy decision, not a
    per-video fault, and must never be retried.
    """


class TenantBreaker:
    """Count terminal per-video failures per tenant; trip past a threshold.

    ``max_failures=None`` never trips (the default, mirroring the batch
    flag); ``0`` trips on the first terminal failure.
    """

    def __init__(self, max_failures: Optional[int] = None):
        if max_failures is not None and max_failures < 0:
            raise ValueError("max_failures must be >= 0 (0 = trip on the "
                             "first failure)")
        self.max_failures = max_failures
        self._failures: Dict[str, int] = {}
        self._open: set = set()

    def record_failure(self, tenant: str) -> bool:
        """Count one terminal failure; True exactly when this one TRIPS the
        breaker (the daemon then drains the tenant's queue once)."""
        self._failures[tenant] = self._failures.get(tenant, 0) + 1
        if (self.max_failures is not None
                and tenant not in self._open
                and self._failures[tenant] > self.max_failures):
            self._open.add(tenant)
            return True
        return False

    def tripped(self, tenant: str) -> bool:
        return tenant in self._open

    def failures(self, tenant: str) -> int:
        return self._failures.get(tenant, 0)

    def open_tenants(self) -> Iterable[str]:
        return sorted(self._open)

    def reset(self, tenant: Optional[str] = None) -> None:
        """Close breakers (all tenants, or one) and zero their counts."""
        if tenant is None:
            self._failures.clear()
            self._open.clear()
        else:
            self._failures.pop(tenant, None)
            self._open.discard(tenant)
