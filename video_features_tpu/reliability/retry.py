"""Bounded retry with exponential backoff, keyed on the error taxonomy.

Only transient failures (see :mod:`.errors`) are retried: re-decoding a corrupt
container burns a full decode pass to learn nothing, while re-running a video
whose ffmpeg child was OOM-killed usually succeeds. Delays grow exponentially
and are capped; the sleep function is injectable so tests assert the schedule
without waiting it out.
"""

from __future__ import annotations

import time
from dataclasses import dataclass
from typing import Callable, Iterator, Optional, TypeVar

from .errors import classify

T = TypeVar("T")


@dataclass(frozen=True)
class RetryPolicy:
    """``attempts`` is the total try count (1 = no retries)."""

    attempts: int = 3
    base_delay: float = 0.5
    max_delay: float = 30.0
    multiplier: float = 2.0

    def __post_init__(self):
        if self.attempts < 1:
            raise ValueError("attempts must be >= 1")
        if self.base_delay < 0 or self.max_delay < 0:
            raise ValueError("delays must be >= 0")

    def delays(self) -> Iterator[float]:
        """Backoff before retry k (k = 1..attempts-1): min(base·mult^(k-1), max)."""
        d = self.base_delay
        for _ in range(self.attempts - 1):
            yield min(d, self.max_delay)
            d *= self.multiplier


def retry_call(
    fn: Callable[[], T],
    policy: RetryPolicy,
    *,
    should_retry: Optional[Callable[[BaseException], bool]] = None,
    sleep: Callable[[float], None] = time.sleep,
    on_retry: Optional[Callable[[BaseException, int, float], None]] = None,
) -> T:
    """Call ``fn`` under ``policy``; retry transient failures with backoff.

    ``should_retry`` defaults to the taxonomy's transient tag
    (:func:`.errors.classify`). ``on_retry(exc, attempt, delay)`` fires before
    each backoff sleep — the extraction loop uses it to release decode-pool
    state so a retry decodes fresh. The final exception is re-raised with an
    ``attempts`` attribute so the failure manifest records the try count.
    """
    if should_retry is None:
        should_retry = lambda exc: classify(exc)[1]  # noqa: E731
    delays = list(policy.delays())
    attempt = 0
    while True:
        attempt += 1
        try:
            return fn()
        except KeyboardInterrupt:
            raise
        except Exception as exc:  # noqa: BLE001 — fault-barrier: classified & re-raised
            retryable = attempt <= len(delays) and should_retry(exc)
            if not retryable:
                # only if unset: a nested retry layer (e.g. the ffmpeg
                # re-encode retry inside open_video) already counted the real
                # attempts — the outer layer must not overwrite them with 1
                if not hasattr(exc, "attempts"):
                    try:
                        exc.attempts = attempt
                    except Exception:  # noqa: BLE001 — fault-barrier: exotic __slots__ exceptions
                        pass
                raise
            delay = delays[attempt - 1]
            if on_retry is not None:
                on_retry(exc, attempt, delay)
            if delay > 0:
                sleep(delay)
