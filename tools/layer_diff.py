"""Per-layer activation-diff harness: Flax models vs independent torch mirrors.

SURVEY.md §4's parity plan: convert random reference-named torch weights into
Flax params, run BOTH implementations layer by layer on the same input, and
report the max abs diff per stage — so a topology error (wrong stride, missing
branch, wrong channel split) is localized to the first diverging layer instead
of surfacing as an end-to-end mismatch (or worse, passing because the oracle
shared the bug — see tests/test_mirror_independence.py).

Usage:
    python tools/layer_diff.py            # report for I3D-rgb and RAFT
    python tools/layer_diff.py --model raft --iters 8

Programmatic: ``i3d_layer_diff()`` / ``raft_layer_diff()`` return
``[(stage, max_abs_diff, ref_scale), ...]`` ordered by execution.
"""

from __future__ import annotations

import argparse
import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# fp32 parity harness: must NOT run on the TPU backend, where fp32 convs default
# to bf16 MXU passes (~2e-3 relative noise that looks like topology divergence).
# The image's sitecustomize pins the axon platform, so force CPU through the API.
os.environ["JAX_PLATFORMS"] = "cpu"
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")


def _diff(name, torch_nchw, flax_nhwc):
    """Compare a torch NC(T)HW tap with a Flax N(T)HWC tap."""
    t = torch_nchw.numpy()
    t = np.moveaxis(t, 1, -1)  # NCHW→NHWC / NCTHW→NTHWC
    f = np.asarray(flax_nhwc)
    assert t.shape == f.shape, f"{name}: {t.shape} vs {f.shape}"
    return name, float(np.abs(t - f).max()), float(np.abs(t).max())


def i3d_layer_diff(modality="rgb", shape=(1, 16, 64, 64), seed=0, sd=None):
    """Layer-wise diffs through the I3D stem + all Mixed blocks.

    ``sd``: a reference-named torch state dict — pass a REAL pretrained
    checkpoint's dict to verify it end to end (tools/verify_parity.py);
    default None uses the deterministic random mirror weights."""
    import torch

    from tools.torch_mirrors import i3d_forward, i3d_random_state_dict

    from video_features_tpu.models.i3d import I3D
    from video_features_tpu.weights.convert_torch import convert_i3d

    rng = np.random.default_rng(seed)
    c = {"rgb": 3, "flow": 2}[modality]
    b, t, h, w = shape
    x = rng.uniform(-1, 1, (b, t, h, w, c)).astype(np.float32)

    if sd is None:
        sd = i3d_random_state_dict(modality, seed=seed)
    taps_t: dict = {}
    i3d_forward(sd, torch.from_numpy(np.moveaxis(x, -1, 1)), features=True, taps=taps_t)

    params = convert_i3d(sd)
    model = I3D(modality=modality)
    _, state = model.apply(
        {"params": params}, x, features=True, capture_intermediates=True, mutable=["intermediates"]
    )
    inter = state["intermediates"]

    rows = []
    for name, t_out in taps_t.items():
        if name in inter:  # Unit3D / Mixed modules (pools are un-named functions)
            rows.append(_diff(name, t_out, inter[name]["__call__"][0]))
    return rows


def raft_layer_diff(shape=(1, 128, 128), iters=4, seed=0, sd=None):
    # NB: H, W ≥ 128 keeps the coarsest corr-pyramid level ≥ 2×2; at 1×1 the
    # reference's align_corners grid mapping divides by (W−1) = 0 (NaN on both
    # sides — real checkpoints never see inputs that small).
    """Stage-wise diffs: encoders, correlation volume, per-iteration flow.

    ``sd``: optional REAL reference state dict (see tools/verify_parity.py)."""
    import torch

    from tools.torch_mirrors import raft_random_state_dict, raft_torch_forward

    from video_features_tpu.models.raft import raft_forward
    from video_features_tpu.weights.convert_torch import convert_raft

    rng = np.random.default_rng(seed)
    b, h, w = shape
    im1 = rng.uniform(0, 255, (b, h, w, 3)).astype(np.float32)
    im2 = rng.uniform(0, 255, (b, h, w, 3)).astype(np.float32)

    if sd is None:
        sd = raft_random_state_dict(seed=seed)
    taps_t: dict = {}
    raft_torch_forward(sd, torch.from_numpy(np.moveaxis(im1, -1, 1)),
                       torch.from_numpy(np.moveaxis(im2, -1, 1)), iters=iters, taps=taps_t)

    params = convert_raft(sd)
    taps_j: dict = {}
    raft_forward(params, im1, im2, iters=iters, taps=taps_j)

    # every tap follows the same layout rule (torch channel-2nd vs flax channel-last,
    # incl. corr_l0: (BHW, 1, H, W) vs (BHW, H, W, 1))
    return [_diff(name, taps_t[name], taps_j[name]) for name in taps_t]


def _report(title, rows, budget=1e-3):
    print(f"\n== {title} ==")
    print(f"{'stage':<28} {'max|Δ|':>12} {'ref max':>12}")
    worst = 0.0
    for name, d, scale in rows:
        flag = "  <-- DIVERGES" if d > budget * max(scale, 1.0) else ""
        print(f"{name:<28} {d:>12.3e} {scale:>12.3e}{flag}")
        worst = max(worst, d / max(scale, 1e-9))
    print(f"worst relative: {worst:.3e}")
    return worst


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--model", choices=["i3d", "raft", "all"], default="all")
    ap.add_argument("--iters", type=int, default=4, help="RAFT update iterations")
    args = ap.parse_args()

    if args.model in ("i3d", "all"):
        _report("I3D rgb (random ref-named weights)", i3d_layer_diff("rgb"))
        _report("I3D flow", i3d_layer_diff("flow"))
    if args.model in ("raft", "all"):
        _report(f"RAFT ({args.iters} iters)", raft_layer_diff(iters=args.iters))


if __name__ == "__main__":
    main()
