"""Measure RAFT's HBM-bounded correlation paths at big-frame geometry.

``on_demand`` (patch gather from pooled f2 — the round-3 path, measured ~40×
slower than ``volume``) vs ``on_demand_matmul`` (round 5: rematerialize each
query chunk's slice of the correlation volume per iteration on the MXU, zero
gathers — models/raft.py::_lookup_on_demand impl='matmul').

Default geometry 1080×1920 (one pair): 1/8-res grid 135×240 → the pyramid
would need ~5.6 GB fp32, past the 4 GiB auto budget — the regime where
``auto`` leaves the volume path (it now resolves to on_demand_matmul;
``VFT_RAFT_ON_DEMAND_IMPL=gather`` reverts — resolve_corr_impl docstring). ``--small``
swaps in 512² (volume fits; all three impls comparable) for a cross-check
against the volume path's numbers.

Results append to ``tools/on_demand_profile.json`` with the same device +
code_rev merge contract as profile_warp_corr.py.
"""

from __future__ import annotations

import argparse
import functools
import json
import os
import subprocess
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

os.environ.setdefault("VFT_ALLOW_RANDOM_WEIGHTS", "1")

from tools._bench_util import enable_compilation_cache, time_fn  # noqa: E402


def main() -> None:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--small", action="store_true",
                    help="512² geometry (volume fits; 3-way comparison)")
    ap.add_argument("--size", default=None,
                    help="explicit HxW override (e.g. 64x64 for a CPU sanity run)")
    ap.add_argument("--impls", default=None,
                    help="comma-separated subset of volume,on_demand,"
                         "on_demand_matmul (default: geometry-appropriate)")
    ap.add_argument("--iters", type=int, default=2)
    args = ap.parse_args()

    import jax
    import jax.numpy as jnp

    enable_compilation_cache()
    print(f"backend: {jax.default_backend()} {jax.devices()[0]}", flush=True)

    from video_features_tpu.models.raft import raft_forward, raft_init_params

    if args.size:
        h, w = (int(v) for v in args.size.split("x"))
    else:
        h, w = (512, 512) if args.small else (1080, 1920)
    h8, w8 = -(-h // 8) * 8, -(-w // 8) * 8  # the extractor's /8 pad
    impls = (args.impls.split(",") if args.impls else
             (["volume", "on_demand", "on_demand_matmul"] if args.small
              else ["on_demand_matmul", "on_demand"]))

    out_path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                            "on_demand_profile.json")
    device = str(jax.devices()[0])
    try:
        code_rev = subprocess.check_output(
            ["git", "rev-parse", "--short", "HEAD"],
            cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
            text=True).strip()
    except Exception:
        code_rev = "unknown"
    results = {}
    try:
        with open(out_path) as f:
            prev = json.load(f)
        if prev.get("device") == device and prev.get("code_rev") == code_rev:
            results = prev
    except Exception:
        pass
    results["device"] = device
    results["code_rev"] = code_rev

    def flush():
        with open(out_path + ".tmp", "w") as f:
            json.dump(results, f, indent=2)
        os.replace(out_path + ".tmp", out_path)

    rng = np.random.default_rng(0)
    params = jax.device_put(raft_init_params(seed=0))

    for dtype_name, dtype in (("bfloat16", jnp.bfloat16),
                              ("float32", jnp.float32)):
        ref = None
        # ONE fixed input pair per dtype: the cross-impl drift check must
        # compare flows computed on the SAME frames
        cmp_rng = np.random.default_rng(7)
        cmp_a = jnp.asarray(cmp_rng.uniform(0, 255, (1, h8, w8, 3))
                            .astype(np.float32))
        cmp_b = jnp.asarray(cmp_rng.uniform(0, 255, (1, h8, w8, 3))
                            .astype(np.float32))
        ref_impl = None
        for impl in impls:
            name = f"raft_1x{h8}x{w8}_{dtype_name}_{impl}"
            try:
                step = jax.jit(functools.partial(
                    raft_forward, corr_impl=impl, dtype=dtype))

                def mk():
                    a = jnp.asarray(rng.uniform(0, 255, (1, h8, w8, 3))
                                    .astype(np.float32))
                    b = jnp.asarray(rng.uniform(0, 255, (1, h8, w8, 3))
                                    .astype(np.float32))
                    return params, a, b

                sec = time_fn(name, step, mk, iters=args.iters)
                results[name] = round(sec * 1e3, 2)  # ms per pair
                flow = np.asarray(step(params, cmp_a, cmp_b), dtype=np.float32)
                if ref is None:
                    # the drift reference is the first impl that SUCCEEDED —
                    # label with its actual name, not impls[0]
                    ref, ref_impl = flow, impl
                else:
                    results[f"{name}_max_px_diff_vs_{ref_impl}"] = round(
                        float(np.abs(flow - ref).max()), 5)
            except Exception as e:  # noqa: BLE001 — per-config barrier
                results[name] = f"FAILED: {str(e)[:200]}"
                print(f"{name}: FAILED {str(e)[:160]}", flush=True)
            flush()

    print(json.dumps({k: v for k, v in results.items()
                      if isinstance(v, (int, float))}), flush=True)


if __name__ == "__main__":
    main()
