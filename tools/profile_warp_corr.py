"""Measure the fused warp+corr kernel vs the XLA composition on TPU.

Per-level shapes are PWC's correlation inputs at a 256² frame (the production
two-stream I3D geometry): level ℓ runs at 256/2^ℓ with PYR_CHANNELS[ℓ-1]
features. Each (impl, dtype, level) is timed with bench.py's methodology
(fresh inputs per call, forced host read, sync subtraction); results append
to ``tools/warp_corr_profile.json``.

Run on the axon TPU; compile failures are caught per-config so one Mosaic
rejection cannot sink the sweep.
"""

from __future__ import annotations

import json
import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

os.environ.setdefault("VFT_ALLOW_RANDOM_WEIGHTS", "1")

from tools._bench_util import enable_compilation_cache, time_fn  # noqa: E402


def main() -> None:
    import jax
    import jax.numpy as jnp

    enable_compilation_cache()
    print(f"backend: {jax.default_backend()} {jax.devices()[0]}", flush=True)

    from video_features_tpu.ops.pallas_corr import warp_corr81
    from video_features_tpu.ops.warp import warp_backward

    rng = np.random.default_rng(0)
    results = {"device": str(jax.devices()[0])}
    out_path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                            "warp_corr_profile.json")

    def flush():
        with open(out_path + ".tmp", "w") as f:
            json.dump(results, f, indent=2)
        os.replace(out_path + ".tmp", out_path)

    b = 16
    # (level, side, channels) at a 256² input; level 6 has no warp
    levels = ((2, 64, 32), (3, 32, 64), (4, 16, 96), (5, 8, 128))

    import functools

    for level, side, c in levels:
        for dtype_name, dtype in (("float32", jnp.float32),
                                  ("bfloat16", jnp.bfloat16)):
            def mk(side=side, c=c, dtype=dtype):
                f1 = jnp.asarray(rng.normal(size=(b, side, side, c))
                                 .astype(np.float32)).astype(dtype)
                f2 = jnp.asarray(rng.normal(size=(b, side, side, c))
                                 .astype(np.float32)).astype(dtype)
                fl = jnp.asarray(rng.uniform(-6, 6, (b, side, side, 2))
                                 .astype(np.float32))
                return f1, f2, fl

            for impl in ("xla", "pallas"):
                name = f"L{level}_{side}x{side}c{c}_{dtype_name}_{impl}"
                step = jax.jit(functools.partial(warp_corr81, impl=impl))
                try:
                    sec = time_fn(name, step, mk, iters=8)
                    results[name] = round(sec * 1e3, 4)  # ms/iter (b=16)
                except Exception as e:  # noqa: BLE001 — per-config barrier
                    results[name] = f"FAILED: {str(e)[:200]}"
                    print(f"{name}: FAILED {str(e)[:160]}", flush=True)
                flush()

            # parity of the compiled kernel vs the composition on-device
            try:
                f1, f2, fl = mk()
                ref = np.asarray(
                    jax.jit(lambda a, b2, fl2: warp_corr81(a, b2, fl2, "xla"))
                    (f1, f2, fl), dtype=np.float32)
                out = np.asarray(
                    jax.jit(lambda a, b2, fl2: warp_corr81(a, b2, fl2, "pallas"))
                    (f1, f2, fl), dtype=np.float32)
                err = float(np.max(np.abs(out - ref)))
                scale = float(np.max(np.abs(ref))) or 1.0
                results[f"L{level}_{dtype_name}_max_abs_err"] = err
                print(f"L{level} {dtype_name} parity: max|Δ|={err:.3e} "
                      f"(max|ref|={scale:.3e})", flush=True)
            except Exception as e:  # noqa: BLE001
                results[f"L{level}_{dtype_name}_max_abs_err"] = f"FAILED: {str(e)[:200]}"
            flush()

    # whole-forward effect: pwc_forward_frames on a 17-frame 256² stack
    from video_features_tpu.models.pwc import pwc_forward_frames, pwc_init_params

    params = pwc_init_params(seed=0)
    params = jax.device_put(params)
    for dtype_name, dtype in (("float32", jnp.float32), ("bfloat16", jnp.bfloat16)):
        for impl in ("xla", "auto"):
            name = f"pwc_frames17_256_{dtype_name}_{impl}"
            step = jax.jit(functools.partial(
                pwc_forward_frames, corr_impl=impl, dtype=dtype))

            def mk_frames():
                return (params, jnp.asarray(
                    rng.uniform(0, 255, (17, 256, 256, 3)).astype(np.float32)))

            try:
                sec = time_fn(name, step, mk_frames, iters=4)
                results[name] = round(sec * 1e3, 4)  # ms per 16-pair stack
            except Exception as e:  # noqa: BLE001
                results[name] = f"FAILED: {str(e)[:200]}"
                print(f"{name}: FAILED {str(e)[:160]}", flush=True)
            flush()

    print(json.dumps(results), flush=True)


if __name__ == "__main__":
    main()
