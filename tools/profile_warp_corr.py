"""Measure the fused warp+corr kernel vs the XLA composition on TPU.

Per-level shapes are PWC's correlation inputs at a 256² frame (the production
two-stream I3D geometry): level ℓ runs at 256/2^ℓ with PYR_CHANNELS[ℓ-1]
features. Each (impl, dtype, level) is timed with bench.py's methodology
(fresh inputs per call, forced host read, sync subtraction); results append
to ``tools/warp_corr_profile.json``.

Run on the axon TPU; compile failures are caught per-config so one Mosaic
rejection cannot sink the sweep.
"""

from __future__ import annotations

import json
import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

os.environ.setdefault("VFT_ALLOW_RANDOM_WEIGHTS", "1")

from tools._bench_util import enable_compilation_cache, time_fn  # noqa: E402


def main() -> None:
    import argparse

    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--levels", default="5,4,3,2",
                    help="comma-separated PWC levels to sweep (subset of 5,4,3,2)")
    ap.add_argument("--forward", action="store_true",
                    help="also run the whole-forward xla/auto/auto_nofused sweep")
    args = ap.parse_args()

    import jax
    import jax.numpy as jnp

    enable_compilation_cache()
    print(f"backend: {jax.default_backend()} {jax.devices()[0]}", flush=True)

    # measure the fused kernel DIRECTLY: the production dispatcher's
    # compile/win allowlist would silently substitute the composition at
    # gated-out shapes, mislabeling composition numbers as kernel data
    from video_features_tpu.ops.pallas_corr import (
        corr81,
        warp_corr81,
        warp_corr81_pallas,
    )
    from video_features_tpu.ops.warp import warp_backward

    rng = np.random.default_rng(0)
    out_path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                            "warp_corr_profile.json")
    device = str(jax.devices()[0])
    import subprocess

    try:
        code_rev = subprocess.check_output(
            ["git", "rev-parse", "--short", "HEAD"],
            cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
            text=True).strip()
    except Exception:
        code_rev = "unknown"
    results = {}
    try:  # merge-update: --levels split runs must not clobber each other —
        # but only same-device SAME-CODE results merge (stale pre-change
        # kernel timings presented as current data would silently poison the
        # allowlist calibration)
        with open(out_path) as f:
            prev = json.load(f)
        if prev.get("device") == device and prev.get("code_rev") == code_rev:
            results = prev
    except Exception:
        pass
    results["device"] = device
    results["code_rev"] = code_rev

    def flush():
        with open(out_path + ".tmp", "w") as f:
            json.dump(results, f, indent=2)
        os.replace(out_path + ".tmp", out_path)

    b = 16
    # (level, side, channels) at a 256² input; level 6 has no warp.
    # SMALL levels first: the Mosaic remote compile of the 64²/32² kernels
    # can wedge for 30+ min on the tunnel, and the small levels are the
    # compile-allowlist candidates — their data must land first.
    levels_all = {5: (5, 8, 128), 4: (4, 16, 96), 3: (3, 32, 64), 2: (2, 64, 32)}
    try:
        levels = tuple(levels_all[int(v)] for v in args.levels.split(","))
    except (KeyError, ValueError):
        ap.error(f"--levels must be a comma-separated subset of "
                 f"{sorted(levels_all)} (got {args.levels!r})")

    import functools

    for level, side, c in levels:
        for dtype_name, dtype in (("float32", jnp.float32),
                                  ("bfloat16", jnp.bfloat16)):
            def mk(side=side, c=c, dtype=dtype):
                f1 = jnp.asarray(rng.normal(size=(b, side, side, c))
                                 .astype(np.float32)).astype(dtype)
                f2 = jnp.asarray(rng.normal(size=(b, side, side, c))
                                 .astype(np.float32)).astype(dtype)
                fl = jnp.asarray(rng.uniform(-6, 6, (b, side, side, 2))
                                 .astype(np.float32))
                return f1, f2, fl

            # "pallas" times warp_corr81_pallas DIRECTLY (bypassing the
            # production allowlist, which would silently substitute the
            # composition at gated-out shapes); "xla" is the gather-warp +
            # fused-XLA-volume composition; "comp" is the PRODUCTION fallback
            # (gather warp + Pallas corr kernels) — the baseline the fused
            # kernel must beat for the allowlist to admit it
            steps = {
                "xla": jax.jit(functools.partial(warp_corr81, impl="xla")),
                "comp": jax.jit(lambda a, b2, fl2: corr81(
                    a, warp_backward(b2, fl2), "auto")),
                "pallas": jax.jit(warp_corr81_pallas),
            }
            for impl in ("xla", "comp", "pallas"):
                name = f"L{level}_{side}x{side}c{c}_{dtype_name}_{impl}"
                try:
                    sec = time_fn(name, steps[impl], mk, iters=8)
                    results[name] = round(sec * 1e3, 4)  # ms/iter (b=16)
                except Exception as e:  # noqa: BLE001 — per-config barrier
                    results[name] = f"FAILED: {str(e)[:200]}"
                    print(f"{name}: FAILED {str(e)[:160]}", flush=True)
                flush()

            # parity of the compiled fused kernel vs the composition on-device
            try:
                f1, f2, fl = mk()
                ref = np.asarray(
                    jax.jit(lambda a, b2, fl2: warp_corr81(a, b2, fl2, "xla"))
                    (f1, f2, fl), dtype=np.float32)
                out = np.asarray(
                    jax.jit(warp_corr81_pallas)(f1, f2, fl), dtype=np.float32)
                err = float(np.max(np.abs(out - ref)))
                scale = float(np.max(np.abs(ref))) or 1.0
                results[f"L{level}_{dtype_name}_max_abs_err"] = err
                print(f"L{level} {dtype_name} parity: max|Δ|={err:.3e} "
                      f"(max|ref|={scale:.3e})", flush=True)
            except Exception as e:  # noqa: BLE001
                results[f"L{level}_{dtype_name}_max_abs_err"] = f"FAILED: {str(e)[:200]}"
            flush()

    if not args.forward:
        print(json.dumps({k: v for k, v in results.items()
                          if not isinstance(v, str)}), flush=True)
        return

    # whole-forward effect: pwc_forward_frames on a 17-frame 256² stack
    from video_features_tpu.models.pwc import pwc_forward_frames, pwc_init_params

    params = pwc_init_params(seed=0)
    params = jax.device_put(params)
    # The round-5 decision matrix for the PWC floor. `auto` (production
    # default) is the gather warp + Pallas volume composition — the fused
    # kernel is OFF under auto until this sweep proves it, so `auto` IS the
    # round-4 "auto_nofused" baseline. The env-tagged configs flip one
    # lowering each: the fused Pallas warp+corr at its admitted levels
    # (VFT_FUSED_WARP_CORR=1), the one-hot MXU warp at ALL levels
    # (VFT_WARP_IMPL=onehot, ops/warp.bilinear_sample_onehot), and both —
    # onehot covering the levels the Mosaic cliff keeps from the fused
    # kernel. User-exported values of both env vars are saved/restored.
    saved_env = {k: os.environ.get(k)
                 for k in ("VFT_FUSED_WARP_CORR", "VFT_WARP_IMPL")}
    matrix = (
        ("xla", "xla", {}),
        ("auto", "auto", {}),
        ("auto", "auto_fused", {"VFT_FUSED_WARP_CORR": "1"}),
        ("auto", "auto_onehot", {"VFT_WARP_IMPL": "onehot"}),
        ("auto", "auto_onehot_fused", {"VFT_WARP_IMPL": "onehot",
                                       "VFT_FUSED_WARP_CORR": "1"}),
    )
    for dtype_name, dtype in (("float32", jnp.float32), ("bfloat16", jnp.bfloat16)):
        for impl, tag, env in matrix:
            name = f"pwc_frames17_256_{dtype_name}_{tag}"
            # clear BOTH knobs first: a user-exported VFT_WARP_IMPL or
            # VFT_FUSED_WARP_CORR must not leak into configs that don't set
            # it, or the baseline rows get measured with the wrong lowering
            for k in saved_env:
                os.environ.pop(k, None)
            for k, v in env.items():
                os.environ[k] = v
            try:
                step = jax.jit(functools.partial(
                    pwc_forward_frames, corr_impl=impl, dtype=dtype))

                def mk_frames():
                    return (params, jnp.asarray(
                        rng.uniform(0, 255, (17, 256, 256, 3)).astype(np.float32)))

                sec = time_fn(name, step, mk_frames, iters=4)
                results[name] = round(sec * 1e3, 4)  # ms per 16-pair stack
            except Exception as e:  # noqa: BLE001
                results[name] = f"FAILED: {str(e)[:200]}"
                print(f"{name}: FAILED {str(e)[:160]}", flush=True)
            finally:
                for k, v in saved_env.items():
                    if v is None:
                        os.environ.pop(k, None)
                    else:
                        os.environ[k] = v
            flush()

    print(json.dumps(results), flush=True)


if __name__ == "__main__":
    main()
