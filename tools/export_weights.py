"""Convert reference checkpoints into the store's ``.npz`` format.

One command per checkpoint the reference loads (SURVEY.md §2.1 #25):

    python tools/export_weights.py --model i3d_rgb   --src i3d_rgb.pt
    python tools/export_weights.py --model raft-sintel --src raft-sintel.pth
    python tools/export_weights.py --model pwc-sintel  --src network-default.pytorch
    python tools/export_weights.py --model r2plus1d_18 --src r2plus1d_18-91a641e6.pth
    python tools/export_weights.py --model resnet50    --src resnet50-0676ba61.pth
    python tools/export_weights.py --model vggish      --src vggish_model.ckpt
    python tools/export_weights.py --model vggish      --src vggish_tf_vars.npz

Output: ``<out_dir>/<model>.npz`` with flat ``a/b/c`` Flax param keys —
resolvable by ``weights.store.resolve_params`` without torch/TF at runtime.

VGGish: the reference restores a TF-slim checkpoint
(``/root/reference/models/vggish/vggish_src/vggish_slim.py:102-129``). A ``.ckpt``
needs tensorflow installed (reads variables via ``tf.train.load_checkpoint``);
alternatively pass an ``.npz`` of raw TF variables (``vggish/conv1/weights`` →
array), which needs no TF.
"""

from __future__ import annotations

import argparse
import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from video_features_tpu.weights.store import looks_like_tf_vars, save_params_npz  # noqa: E402

TORCH_CONVERTERS = {
    "resnet50": "convert_resnet50",
    "r2plus1d_18": "convert_r21d",
    "i3d_rgb": "convert_i3d",
    "i3d_flow": "convert_i3d",
    "raft-sintel": "convert_raft",
    "raft-kitti": "convert_raft",
    "pwc-sintel": "convert_pwc",
}


def _strip_module_prefix(sd: dict) -> dict:
    """The reference wraps RAFT in DataParallel only to match 'module.'-prefixed
    checkpoint keys (extract_raft.py:58-59); strip instead of wrapping."""
    if sd and all(k.startswith("module.") for k in sd):
        return {k[len("module."):]: v for k, v in sd.items()}
    return sd


def convert_torch_checkpoint(model: str, src: str) -> dict:
    import torch

    from video_features_tpu.weights import convert_torch as ct

    sd = torch.load(src, map_location="cpu", weights_only=True)
    if isinstance(sd, dict) and "state_dict" in sd:
        sd = sd["state_dict"]
    sd = _strip_module_prefix(sd)
    return getattr(ct, TORCH_CONVERTERS[model])(sd)


def convert_vggish_checkpoint(src: str) -> dict:
    from video_features_tpu.models.vggish import convert_tf_vggish

    if src.endswith(".npz"):
        with np.load(src) as z:
            tf_vars = {k: z[k] for k in z.files}
        if not looks_like_tf_vars(tf_vars):
            raise ValueError(f"{src}: not a TF-variables npz (expected */weights, */biases)")
        return convert_tf_vggish(tf_vars)
    try:
        import tensorflow as tf  # optional: only needed for raw .ckpt input
    except ImportError as e:
        raise SystemExit(
            f"reading {src} requires tensorflow; alternatively dump the checkpoint "
            "variables to an .npz (keys like 'vggish/conv1/weights') and pass that"
        ) from e
    reader = tf.train.load_checkpoint(src)
    tf_vars = {
        name: reader.get_tensor(name)
        for name in reader.get_variable_to_shape_map()
        if name.startswith("vggish/")
    }
    return convert_tf_vggish(tf_vars)


def main() -> None:
    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    ap.add_argument("--model", required=True, choices=sorted([*TORCH_CONVERTERS, "vggish"]))
    ap.add_argument("--src", required=True, help="torch .pt/.pth, TF .ckpt, or TF-vars .npz")
    ap.add_argument("--out_dir", default="./checkpoints")
    args = ap.parse_args()

    if args.model == "vggish":
        params = convert_vggish_checkpoint(args.src)
    else:
        params = convert_torch_checkpoint(args.model, args.src)

    os.makedirs(args.out_dir, exist_ok=True)
    out = os.path.join(args.out_dir, f"{args.model}.npz")
    save_params_npz(out, params)
    n = sum(1 for _ in np.load(out).files)
    print(f"wrote {out} ({n} arrays)")


if __name__ == "__main__":
    main()
