"""Stage-level PWC-Net timing: pyramid extractor vs cost volumes vs warps vs
dense decoders.

Measured (v5e, batch 16 × 256², fp32, round 3): full 60.7 ms vs full_frames
(shared per-frame pyramid) 57.9 ms — only ~5%, revising the round-2 reading:
the standalone extractor2x number (20-36 ms, run-dependent) is dominated by
MATERIALIZING all 12 level outputs to HBM, while inside the full forward the
pyramid fuses into its consumers and costs little. The step is bound by the
coarse-to-fine DenseNet decoders + cost volumes + warps, which are
conv-dominated → the effective lever is ``--flow_dtype bfloat16``, not
further encoder sharing. (Shared frames still matter for RAFT, whose fnet is
a real 17 ms stage.)

Same methodology as the other profilers (tools/_bench_util). Stages:

- extractor2x: both 6-level feature pyramids
- corr_all:    the 6 cost volumes (level 6 no-warp + 5 warped-target volumes)
               on fixed features (no decoder chain)
- warp_all_{gather,onehot}: the 4 decoder Backward warps (levels 5..2)
               on fixed features/flows, per lowering
- full:        pwc_forward (xla cost volume)

Run: python tools/profile_pwc.py [batch] [side]
"""

from __future__ import annotations

import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
os.environ.setdefault("VFT_ALLOW_RANDOM_WEIGHTS", "1")

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402

from _bench_util import enable_compilation_cache, time_fn  # noqa: E402

enable_compilation_cache()

from video_features_tpu.models import pwc as P  # noqa: E402
from video_features_tpu.ops.pallas_corr import corr81_xla  # noqa: E402
from video_features_tpu.ops.warp import warp_backward  # noqa: E402


def main():
    b = int(sys.argv[1]) if len(sys.argv) > 1 else 16
    side = int(sys.argv[2]) if len(sys.argv) > 2 else 256
    rng = np.random.default_rng(0)
    params = jax.device_put(P.pwc_init_params(0))
    print(f"backend={jax.default_backend()} batch={b} side={side}", flush=True)

    # level dims at a /64-aligned input (PWC resizes internally; profile at the
    # post-resize geometry): level l has side/2^l and these channel widths
    chans = {1: 16, 2: 32, 3: 64, 4: 96, 5: 128, 6: 196}

    def frames():
        return jnp.asarray(rng.uniform(0, 255, (b, side, side, 3)).astype(np.float32))

    def feats(level):
        s = side // (2 ** level)
        return jnp.asarray(
            rng.standard_normal((b, s, s, chans[level])).astype(np.float32))

    def flows(level):
        s = side // (2 ** level)
        return jnp.asarray(rng.standard_normal((b, s, s, 2)).astype(np.float32) * 2)

    # --- both feature pyramids ---
    @jax.jit
    def extractor2x(p, x1, x2):
        ext = p["moduleExtractor"]
        return P._pyramid(ext, x1), P._pyramid(ext, x2)

    time_fn("extractor2x", extractor2x, lambda: (params, frames(), frames()))

    # --- 6 cost volumes on fixed features ---
    @jax.jit
    def corr_all(*fs):
        outs = []
        for i in range(0, len(fs), 2):
            outs.append(corr81_xla(fs[i], fs[i + 1]))
        return outs

    def mk_corr():
        out = []
        for level in (2, 3, 4, 5, 6):
            out += [feats(level), feats(level)]
        return tuple(out)

    time_fn("corr_all", corr_all, mk_corr)

    # --- the 4 decoder warps (levels 5..2; level 6 has no prior flow)
    #     on fixed features/flows, each warp lowering ---
    def mk_warp():
        out = []
        for level in (2, 3, 4, 5):
            out += [feats(level), flows(level)]
        return tuple(out)

    for warp_impl in ("gather", "onehot"):
        @jax.jit
        def warp_all(*args, warp_impl=warp_impl):
            outs = []
            for i in range(0, len(args), 2):
                outs.append(warp_backward(args[i], args[i + 1], warp_impl))
            return outs

        time_fn(f"warp_all_{warp_impl}", warp_all, mk_warp)

    # --- full forward ---
    @jax.jit
    def full(p, x1, x2):
        return P.pwc_forward(p, x1, x2)

    time_fn("full", full, lambda: (params, frames(), frames()))

    # --- shared-frame forward: b pairs from b+1 frames, pyramid once/frame ---
    def frames_plus1():
        return jnp.asarray(
            rng.uniform(0, 255, (b + 1, side, side, 3)).astype(np.float32))

    @jax.jit
    def full_frames(p, fr):
        return P.pwc_forward_frames(p, fr)

    time_fn("full_frames", full_frames, lambda: (params, frames_plus1()))

    @jax.jit
    def full_frames_bf16(p, fr):
        return P.pwc_forward_frames(p, fr, dtype=jnp.bfloat16)

    time_fn("full_frames_bf16", full_frames_bf16, lambda: (params, frames_plus1()))


if __name__ == "__main__":
    main()
