"""Measure the reference computation in torch on this host → BASELINE.json "measured".

The reference publishes no benchmark numbers (BASELINE.md), so this script anchors
``vs_baseline`` by timing the torch equivalents of the reference's hot paths
(architectures mirrored 1:1 from the reference source in tools/torch_mirrors.py):

- I3D-rgb: one 64-frame 224² clip forward (/root/reference/models/i3d/i3d_net.py:160-274)
- RAFT: one 256² frame-pair, 20 GRU iterations (/root/reference/models/raft/raft_src/raft.py:115-174)
- ResNet-50: 224² frames (/root/reference/models/resnet50/extract_resnet50.py:54)

Numbers are recorded with hardware metadata; on this build host that is torch-CPU
(the reference's CUDA path has no GPU here). Run once; bench.py reads the result.

Usage: python tools/measure_reference.py [--quick]
"""

from __future__ import annotations

import argparse
import json
import os
import platform
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import torch  # noqa: E402

from tools.torch_mirrors import (  # noqa: E402
    ResNet50,
    i3d_forward,
    i3d_random_state_dict,
    raft_random_state_dict,
    raft_torch_forward,
    random_init_,
)

BASELINE_PATH = os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "BASELINE.json")


def _time(fn, n: int = 1) -> float:
    fn()  # warmup (allocator, thread pool)
    t0 = time.perf_counter()
    for _ in range(n):
        fn()
    return (time.perf_counter() - t0) / n


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--quick", action="store_true", help="16-frame i3d clip instead of 64")
    args = ap.parse_args()

    torch.set_grad_enabled(False)
    rng = np.random.default_rng(0)
    results = {}

    # I3D-rgb: clips/sec at the reference geometry (64×224², extract_i3d.py:27,59-63)
    frames = args.quick and 16 or 64
    sd = i3d_random_state_dict("rgb")
    clip = torch.from_numpy(rng.uniform(-1, 1, (1, 3, frames, 224, 224)).astype(np.float32))
    dt = _time(lambda: i3d_forward(sd, clip, features=True))
    results["i3d_rgb_clips_per_sec"] = (frames / 64.0) / dt  # normalize to 64-frame clips

    # RAFT: flow pairs/sec at the I3D-flow context size (256², 20 iterations)
    rsd = raft_random_state_dict()
    im = torch.from_numpy(rng.uniform(0, 255, (1, 3, 256, 256)).astype(np.float32))
    im2 = torch.from_numpy(rng.uniform(0, 255, (1, 3, 256, 256)).astype(np.float32))
    dt = _time(lambda: raft_torch_forward(rsd, im, im2, iters=20))
    results["raft_pairs_per_sec"] = 1.0 / dt
    # a RAFT-flow "clip" in the north-star metric = 64 consecutive pairs
    results["raft_flow_clips_per_sec"] = 1.0 / (dt * 64.0)

    # ResNet-50: frames/sec at 224² (batch 4 amortizes framework overhead)
    model = random_init_(ResNet50()).eval()
    batch = torch.from_numpy(rng.uniform(-2, 2, (4, 3, 224, 224)).astype(np.float32))
    dt = _time(lambda: model(batch, features=True))
    results["resnet50_fps"] = 4.0 / dt

    results = {k: round(v, 6) for k, v in results.items()}
    meta = {
        "hardware": f"torch-{torch.__version__} CPU, {torch.get_num_threads()} thread(s), {platform.processor() or platform.machine()}",
        "note": "reference torch computation timed on the build host (no GPU available); "
        "architectures mirrored from /root/reference (see tools/torch_mirrors.py)",
    }

    with open(BASELINE_PATH) as f:
        baseline = json.load(f)
    baseline["measured"] = {**results, **meta}
    with open(BASELINE_PATH, "w") as f:
        json.dump(baseline, f, indent=2)
    print(json.dumps(baseline["measured"], indent=2))


if __name__ == "__main__":
    main()
