"""Bisect which construct of the fused warp+corr kernel the axon Mosaic
backend rejects (HTTP 500 = compile-helper subprocess crash, no diagnostics).

Each probe is a minimal pallas_call exercising ONE ingredient; run on TPU:
    python tools/probe_mosaic.py
"""

from __future__ import annotations

import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

H = W = 64
C = 32
P = 96  # chunk pixels
HW = H * W


def probe(name, kernel, out_shape, *args):
    try:
        out = pl.pallas_call(kernel, out_shape=out_shape)(*args)
        out.block_until_ready()
        print(f"{name}: OK", flush=True)
        return True
    except Exception as e:  # noqa: BLE001
        print(f"{name}: FAIL {str(e)[:160]}", flush=True)
        return False


def main():
    print(f"backend: {jax.default_backend()}", flush=True)
    rng = np.random.default_rng(0)
    f2 = jnp.asarray(rng.normal(size=(HW, C)).astype(np.float32))
    xy = jnp.asarray(rng.uniform(0, 60, (4, 24)).astype(np.float32))  # (rows, halo)

    # 1. int32 iota (P, HW) + compare vs (P, 1) + cast + dot
    def k1(f2_ref, idx_ref, o_ref):
        iota = jax.lax.broadcasted_iota(jnp.int32, (P, HW), 1)
        onehot = (idx_ref[...] == iota).astype(jnp.float32)
        o_ref[...] = jax.lax.dot_general(
            onehot, f2_ref[...], (((1,), (0,)), ((), ())),
            precision=jax.lax.Precision.HIGHEST,
            preferred_element_type=jnp.float32)

    idx = jnp.asarray(rng.integers(0, HW, (P, 1)).astype(np.int32))
    probe("onehot_dot_highest", k1,
          jax.ShapeDtypeStruct((P, C), jnp.float32), f2, idx)

    # 1b. same at DEFAULT precision
    def k1b(f2_ref, idx_ref, o_ref):
        iota = jax.lax.broadcasted_iota(jnp.int32, (P, HW), 1)
        onehot = (idx_ref[...] == iota).astype(jnp.float32)
        o_ref[...] = jax.lax.dot_general(
            onehot, f2_ref[...], (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    probe("onehot_dot_default", k1b,
          jax.ShapeDtypeStruct((P, C), jnp.float32), f2, idx)

    # 2. reshape (rows, halo) -> (rows*halo, 1)
    def k2(x_ref, o_ref):
        o_ref[...] = x_ref[...].reshape(4 * 24, 1)

    probe("reshape_2d_to_col", k2,
          jax.ShapeDtypeStruct((96, 1), jnp.float32), xy)

    # 3. floor/clip/astype int32 on 2d
    def k3(x_ref, o_ref):
        x0 = jnp.floor(x_ref[...])
        o_ref[...] = jnp.clip(x0, 0, 63).astype(jnp.int32)

    probe("floor_clip_int", k3,
          jax.ShapeDtypeStruct((4, 24), jnp.int32), xy)

    # 4. reshape (P, C) -> (rows, halo_c...) back to 3d
    sel = jnp.asarray(rng.normal(size=(96, C)).astype(np.float32))

    def k4(x_ref, o_ref):
        o_ref[...] = x_ref[...].reshape(4, 24, C)

    probe("reshape_col_to_3d", k4,
          jax.ShapeDtypeStruct((4, 24, C), jnp.float32), sel)

    # 5. concatenate along axis 0
    def k5(x_ref, o_ref):
        o_ref[...] = jnp.concatenate([x_ref[...], x_ref[...]], axis=0)

    probe("concat_axis0", k5,
          jax.ShapeDtypeStruct((8, 24), jnp.float32), xy)

    # 6. dynamic slice with program_id-free dslice on a 4d ref
    flow = jnp.asarray(rng.normal(size=(1, 72, 72, 2)).astype(np.float32))

    def k6(f_ref, o_ref):
        o_ref[...] = f_ref[0, pl.dslice(4, 4), pl.dslice(0, 24), :]

    probe("dslice_4d", k6,
          jax.ShapeDtypeStruct((4, 24, 2), jnp.float32), flow)

    # 7. int mod/div on (P,1) iota (alternative to the reshape)
    def k7(o_ref):
        pi = jax.lax.broadcasted_iota(jnp.int32, (P, 1), 0)
        o_ref[...] = (pi // 24) * 100 + pi % 24

    probe("iota_divmod_col", k7, jax.ShapeDtypeStruct((P, 1), jnp.int32))

    # 8. the 81-tap static-shift corr on a (24,24,C) tile (known-good shape
    #    from _corr81_kernel_tiled, sanity)
    warped = jnp.asarray(rng.normal(size=(24, 24, C)).astype(np.float32))
    f1t = jnp.asarray(rng.normal(size=(16, 16, C)).astype(np.float32))

    def k8(w_ref, f1_ref, o_ref):
        taps = []
        f1 = f1_ref[...]
        for dy in range(9):
            for dx in range(9):
                taps.append(jnp.sum(
                    f1 * w_ref[dy:dy + 16, dx:dx + 16, :], axis=-1) / C)
        o_ref[...] = jnp.stack(taps, axis=-1)

    probe("taps81", k8, jax.ShapeDtypeStruct((16, 16, 81), jnp.float32),
          warped, f1t)


if __name__ == "__main__":
    main()
