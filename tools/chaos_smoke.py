#!/usr/bin/env python
"""Serving chaos smoke: SIGKILL the daemon at each durability seam, restart,
and assert exactly-once recovery (docs/reliability.md "Serving chaos seams",
docs/serving.md "Crash recovery").

The unit layer (tests/test_wal.py, tests/test_service.py) proves the WAL and
replay mechanics in-process; this script proves them across REAL process
death, driving the CLI surface as an operator would:

1. a batch CLI run produces the reference outputs;
2. for each chaos seam, a daemon subprocess runs with ``VFT_FAULTS`` set to
   ``kill`` (``os._exit(137)``) at that seam:

   - ``wal_sync:kill``  — post-accept, pre-WAL-fsync (the torn-ack crash);
   - ``pool_worker:kill`` — a decode worker dies mid-video;
   - ``device:kill``    — mid-batch, just before the device step dispatches;
   - ``publish:kill``   — post-extract, pre-result-record (outputs + the
     done-manifest exist, the acknowledgement does not);

   a request is dropped into the spool, the daemon dies with exit 137, and a
   restart of the SAME spool (no fault) must recover via the admission WAL:
   the ``done`` result record appears, outputs are byte-identical to the
   batch run, the done-manifest holds each video EXACTLY once (no double
   extraction), and the WAL compacts back to empty after the drain;
3. an ENOSPC drill (``wal_append:raise``) proves degrade-never-crash on a
   live daemon: submits keep completing, ``healthz`` flags ``durable: false``.

Runs on CPU with deterministic random weights::

    JAX_PLATFORMS=cpu VFT_ALLOW_RANDOM_WEIGHTS=1 python tools/chaos_smoke.py

Exit code 0 = pass; any assertion or timeout raises.
"""

import glob
import json
import os
import signal
import socket
import subprocess
import sys
import tempfile
import time

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
TIMEOUT = float(os.environ.get("VFT_SMOKE_TIMEOUT", "600"))

# (name, VFT_FAULTS spec, what the kill simulates, extra daemon flags).
# The pool_worker seam needs a real decode pool: with the default
# --decode_workers 1 the daemon decodes inline and the seam never runs.
KILL_SEAMS = [
    ("wal_sync", "wal_sync:kill", "post-accept, pre-WAL-fsync", ()),
    ("pool_worker", "pool_worker:kill", "decode worker dies mid-video",
     ("--decode_workers", "2")),
    ("device", "device:kill", "mid-batch, pre-device-step", ()),
    ("publish", "publish:kill", "post-extract, pre-result-publish", ()),
]


def write_video(path, frames, size=(32, 24)):
    import cv2

    w = cv2.VideoWriter(path, cv2.VideoWriter_fourcc(*"mp4v"), 10.0, size)
    rng = np.random.default_rng(frames)
    for _ in range(frames):
        w.write(rng.integers(0, 256, (size[1], size[0], 3), dtype=np.uint8))
    w.release()
    return path


def cli(out_dir, *extra):
    return [sys.executable, os.path.join(REPO, "main.py"),
            "--feature_type", "resnet50", "--on_extraction", "save_numpy",
            "--batch_size", "4", "--output_path", out_dir, *extra]


def daemon_cmd(out_dir, spool, *extra):
    return cli(out_dir, "--serve", "--spool_dir", spool,
               "--idle_flush_sec", "0.05", "--spool_poll_sec", "0.05",
               *extra)


def outputs(out_dir):
    return {os.path.basename(p): np.load(p)
            for p in glob.glob(os.path.join(out_dir, "resnet50", "*.npy"))}


def sock_op(sock_path, op):
    with socket.socket(socket.AF_UNIX, socket.SOCK_STREAM) as s:
        s.settimeout(10.0)
        s.connect(sock_path)
        s.sendall(json.dumps(op).encode() + b"\n")
        buf = b""
        while b"\n" not in buf:
            chunk = s.recv(65536)
            if not chunk:
                break
            buf += chunk
    return json.loads(buf.split(b"\n", 1)[0].decode())


def drop_request(spool, request_id, payload):
    tmp = os.path.join(spool, f".{request_id}.tmp")
    with open(tmp, "w") as f:
        json.dump(payload, f)
    os.replace(tmp, os.path.join(spool, f"{request_id}.json"))


def await_results(daemon, paths, deadline):
    while time.time() < deadline:
        if daemon.poll() is not None:
            raise AssertionError(
                f"daemon exited early with {daemon.returncode}")
        if all(os.path.exists(p) for p in paths):
            return
        time.sleep(0.2)
    raise AssertionError("timed out waiting for result records")


def wal_records(spool):
    path = os.path.join(spool, "admission.wal")
    if not os.path.exists(path):
        return []
    with open(path) as f:
        recs = []
        for line in f:
            try:
                recs.append(json.loads(line))
            except ValueError:
                recs.append({"rec": "torn"})  # a torn tail is expected here
        return recs


def kill_seam_drill(name, fault, desc, extra, env, root, videos, want):
    spool = os.path.join(root, f"spool_{name}")
    os.makedirs(spool)
    serve_out = os.path.join(root, f"serve_{name}")
    result = os.path.join(spool, "results", "req_chaos.result.json")

    print(f"[chaos] seam {name}: {desc} (VFT_FAULTS={fault})")
    daemon = subprocess.Popen(daemon_cmd(serve_out, spool, *extra),
                              env={**env, "VFT_FAULTS": fault})
    try:
        drop_request(spool, "req_chaos", {"tenant": "alice",
                                          "videos": videos})
        rc = daemon.wait(timeout=TIMEOUT)
    finally:
        if daemon.poll() is None:
            daemon.kill()
            daemon.wait()
    assert rc == 137, f"seam {name}: expected kill exit 137, got {rc}"
    # the crash window: the request was claimed from the spool and admitted
    # to the WAL, but never acknowledged
    assert not os.path.exists(result), \
        f"seam {name}: result record published before the kill"
    admitted = [r for r in wal_records(spool)
                if r.get("rec") == "admitted" and r.get("request") == "req_chaos"]
    assert admitted, f"seam {name}: no admitted WAL record survived the kill"

    print(f"[chaos] seam {name}: restarting over the same spool (recovery)")
    daemon = subprocess.Popen(daemon_cmd(serve_out, spool, *extra), env=env)
    try:
        await_results(daemon, [result], time.time() + TIMEOUT)
        daemon.send_signal(signal.SIGTERM)
        assert daemon.wait(timeout=TIMEOUT) == 0, daemon.returncode
    finally:
        if daemon.poll() is None:
            daemon.kill()
            daemon.wait()

    with open(result) as f:
        record = json.load(f)
    assert record["state"] == "done", (name, record)
    assert sorted(record["done"]) == sorted(
        os.path.abspath(v) for v in videos), (name, record)

    got = outputs(serve_out)
    assert set(got) == set(want), (name, sorted(got), sorted(want))
    for fname in sorted(want):
        assert got[fname].tobytes() == want[fname].tobytes(), \
            f"seam {name}: {fname} differs from the batch run after recovery"

    # exactly-once: every video appears ONCE in the done-manifest — a seam
    # that re-extracted already-published work would append a second record
    with open(os.path.join(serve_out, "resnet50",
                           ".done_manifest.jsonl")) as f:
        done = [json.loads(line)["video"] for line in f]
    assert sorted(set(done)) == sorted(
        os.path.abspath(v) for v in videos), (name, done)
    assert len(done) == len(set(done)), \
        f"seam {name}: duplicate done-manifest records — not exactly-once"

    # the acknowledged+published request resolved its WAL entry; the drain
    # compacted the log back to empty
    assert wal_records(spool) == [], (name, wal_records(spool))
    print(f"[chaos] seam {name}: recovered exactly-once, byte parity ok")


def enospc_drill(env, root, videos):
    """wal_append:raise = the ENOSPC drill: the daemon must keep serving
    (non-durable, loudly flagged), never crash."""
    spool = os.path.join(root, "spool_enospc")
    os.makedirs(spool)
    serve_out = os.path.join(root, "serve_enospc")
    result = os.path.join(spool, "results", "req_degraded.result.json")
    print("[chaos] ENOSPC drill: VFT_FAULTS=wal_append:raise "
          "(degrade, keep serving)")
    daemon = subprocess.Popen(daemon_cmd(serve_out, spool),
                              env={**env, "VFT_FAULTS": "wal_append:raise"})
    try:
        drop_request(spool, "req_degraded", {"tenant": "alice",
                                             "videos": videos})
        await_results(daemon, [result], time.time() + TIMEOUT)
        health = sock_op(os.path.join(spool, "control.sock"),
                         {"op": "healthz"})
        assert health["ok"], health
        assert health["wal"]["enabled"] is True, health["wal"]
        assert health["wal"]["durable"] is False, health["wal"]
        daemon.send_signal(signal.SIGTERM)
        assert daemon.wait(timeout=TIMEOUT) == 0, daemon.returncode
    finally:
        if daemon.poll() is None:
            daemon.kill()
            daemon.wait()
    with open(result) as f:
        record = json.load(f)
    assert record["state"] == "done", record
    print("[chaos] ENOSPC drill: served while degraded, healthz flagged it")


def main() -> int:
    env = {**os.environ, "JAX_PLATFORMS": "cpu",
           "VFT_ALLOW_RANDOM_WEIGHTS": "1"}
    env.pop("VFT_FAULTS", None)
    root = tempfile.mkdtemp(prefix="vft_chaos_smoke_")
    videos = [write_video(os.path.join(root, f"v{i}.mp4"), n)
              for i, n in enumerate((3, 6))]

    print("[chaos] batch reference run")
    subprocess.run(cli(os.path.join(root, "batch"), "--video_paths", *videos),
                   env=env, check=True, timeout=TIMEOUT)
    want = outputs(os.path.join(root, "batch"))
    assert want, "batch reference run produced no outputs"

    for name, fault, desc, extra in KILL_SEAMS:
        kill_seam_drill(name, fault, desc, extra, env, root, videos, want)
    enospc_drill(env, root, videos)

    print(f"[chaos] PASS: {len(KILL_SEAMS)} kill seams recovered "
          "exactly-once with byte parity; ENOSPC degraded without a crash")
    return 0


if __name__ == "__main__":
    sys.exit(main())
