#!/bin/bash
# Round-5 TPU measurement runbook — run when the axon tunnel is up.
# Ordered most-important-first so a mid-run tunnel drop still lands the
# headline record. Logs to /tmp/runbook/; each tool merge-updates its own
# JSON record (bench_details.json / warp_corr_profile.json /
# on_demand_profile.json) so partial runs refine rather than clobber.
set -u
cd "$(dirname "$0")/.."
export JAX_COMPILATION_CACHE_DIR=${JAX_COMPILATION_CACHE_DIR:-/tmp/jax_cache}
L=/tmp/runbook
mkdir -p "$L"
run() {  # run <tag> <cmd...>
  echo "=== $1 start $(date -u +%H:%M:%S) ===" | tee -a "$L/runbook.log"
  shift
  "$@" > "$L/$1.log" 2>&1
  echo "=== rc=$? end $(date -u +%H:%M:%S) ===" | tee -a "$L/runbook.log"
}
# 1. the round's must-have: headline + device-step + e2e entries
run bench env VFT_BENCH_BUDGET=2400 python bench.py
# 2. PWC floor decision: per-level (cheap levels) + whole-forward matrix
#    (auto / auto_fused / auto_onehot / auto_onehot_fused)
run warpcorr python tools/profile_warp_corr.py --levels 5,4 --forward
# 3. RAFT big-frame paths: on_demand_matmul vs on_demand at 1080p
run ondemand python tools/profile_on_demand.py
# 4. I3D clips_per_batch knee at 224² (verdict item 5)
run i3d_c8 python tools/profile_i3d.py 8 64
run i3d_c16 python tools/profile_i3d.py 16 64
# 5. PWC stage attribution incl. gather-vs-onehot warp microbench
run pwc_stages python tools/profile_pwc.py 16 256
echo "RUNBOOK COMPLETE $(date -u)" | tee -a "$L/runbook.log"
