"""Stage-level RAFT timing on the live backend: where do the 1.5 s/step go?

Times each stage of ``raft_forward`` (batch 16 × 256², the bench config) as its
own jitted program with unique inputs per call (defeats the axon tunnel's
result memoization — see bench.py methodology notes):

- encoders: fnet(x1) + fnet(x2) + cnet(x1)
- pyramid:  all-pairs einsum + 3 avg-pools
- lookup20: 20 chained 4-level 9×9 window lookups (volume impl)
- gru20:    20 scan iterations with the lookup replaced by a fixed corr tensor
- full:     raft_forward volume / on_demand

Run: python tools/profile_raft.py [batch] [side]
"""

from __future__ import annotations

import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
os.environ.setdefault("VFT_ALLOW_RANDOM_WEIGHTS", "1")

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402
from jax import lax  # noqa: E402

from _bench_util import enable_compilation_cache, time_fn  # noqa: E402

enable_compilation_cache()

from video_features_tpu.models import raft as R  # noqa: E402


def main():
    b = int(sys.argv[1]) if len(sys.argv) > 1 else 16
    side = int(sys.argv[2]) if len(sys.argv) > 2 else 256
    h8 = w8 = side // 8
    rng = np.random.default_rng(0)
    params = jax.device_put(R.raft_init_params(0))
    print(f"backend={jax.default_backend()} batch={b} side={side}", flush=True)

    def frames():
        return jnp.asarray(rng.uniform(0, 255, (b, side, side, 3)).astype(np.float32))

    def feats():
        return jnp.asarray(rng.standard_normal((b, h8, w8, 256)).astype(np.float32))

    def small(c):
        return jnp.asarray(rng.standard_normal((b, h8, w8, c)).astype(np.float32))

    # --- encoders ---
    @jax.jit
    def encoders(p, x1, x2):
        f1 = R._encoder(p["fnet"], 2.0 * x1 / 255.0 - 1.0, "instance")
        f2 = R._encoder(p["fnet"], 2.0 * x2 / 255.0 - 1.0, "instance")
        c = R._encoder(p["cnet"], 2.0 * x1 / 255.0 - 1.0, "batch")
        return f1, f2, c

    time_fn("encoders", encoders, lambda: (params, frames(), frames()))

    # --- pyramid build ---
    @jax.jit
    def pyramid(f1, f2):
        return R._build_pyramid(f1, f2)

    time_fn("pyramid", pyramid, lambda: (feats(), feats()))

    # --- 20 lookups (volume: matmul vs gather) ---
    # the drift term consumes EVERY corr channel: a coords+corr[..., :2] probe
    # lets XLA dead-code-eliminate 322 of 324 lookup channels (first profile
    # run under-reported the gather cost 4×)
    def lookup20_impl(impl):
        @jax.jit
        def lookup20(f1, f2, flow0):
            pyr = R._build_pyramid(f1, f2)
            coords0 = R.coords_grid(b, h8, w8)

            def body(coords, _):
                corr = R._lookup(pyr, coords, impl)
                drift = jnp.stack([corr.sum(-1), corr.max(-1)], axis=-1)
                return coords + drift * 1e-4, None

            coords, _ = lax.scan(body, coords0 + flow0, None, length=R.ITERS)
            return coords

        return lookup20

    time_fn("lookup20_mm", lookup20_impl("matmul"),
            lambda: (feats(), feats(), small(2)))
    time_fn("lookup20_ga", lookup20_impl("gather"),
            lambda: (feats(), feats(), small(2)))

    # --- 20 lookups (on-demand) ---
    @jax.jit
    def lookup20_od(f1, f2, flow0):
        pyr = R._build_f2_pyramid(f2)
        coords0 = R.coords_grid(b, h8, w8)

        def body(coords, _):
            corr = R._lookup_on_demand(f1, pyr, coords)
            drift = jnp.stack([corr.sum(-1), corr.max(-1)], axis=-1)
            return coords + drift * 1e-4, None

        coords, _ = lax.scan(body, coords0 + flow0, None, length=R.ITERS)
        return coords

    time_fn("lookup20_od", lookup20_od, lambda: (feats(), feats(), small(2)))

    # --- 20 GRU iterations with fixed corr ---
    n_corr = R.CORR_LEVELS * (2 * R.CORR_RADIUS + 1) ** 2

    @jax.jit
    def gru20(p, corr, net0, inp):
        up = p["update_block"]
        coords0 = R.coords_grid(b, h8, w8)

        def body(carry, _):
            net, coords1 = carry
            flow = coords1 - coords0
            motion = R._motion_encoder(up["encoder"], flow, corr)
            net = R._sep_conv_gru(up["gru"], net, jnp.concatenate([inp, motion], -1))
            delta = R.conv2d(up["flow_head"]["conv2"],
                             R._relu(R.conv2d(up["flow_head"]["conv1"], net, 1, 1)), 1, 1)
            return (net, coords1 + delta), None

        (net, coords1), _ = lax.scan(body, (net0, coords0), None, length=R.ITERS)
        mask = 0.25 * R.conv2d(up["mask.2"], R._relu(R.conv2d(up["mask.0"], net, 1, 1)), 1, 0)
        return R._convex_upsample(coords1 - coords0, mask)

    time_fn("gru20", gru20,
            lambda: (params, small(n_corr), small(R.HIDDEN_DIM), small(R.CONTEXT_DIM)))

    # --- full forward ---
    @jax.jit
    def full(p, x1, x2):
        return R.raft_forward(p, x1, x2)

    time_fn("full_volume", full, lambda: (params, frames(), frames()))

    @jax.jit
    def full_gather(p, x1, x2):
        return R.raft_forward(p, x1, x2, corr_impl="volume_gather")

    time_fn("full_gather", full_gather, lambda: (params, frames(), frames()))

    @jax.jit
    def full_od(p, x1, x2):
        return R.raft_forward(p, x1, x2, corr_impl="on_demand")

    time_fn("full_od", full_od, lambda: (params, frames(), frames()))

    # --- shared-frame forward: b pairs from b+1 frames, fnet once/frame ---
    def frames_plus1():
        return jnp.asarray(
            rng.uniform(0, 255, (b + 1, side, side, 3)).astype(np.float32))

    @jax.jit
    def full_frames(p, fr):
        return R.raft_forward_frames(p, fr)

    time_fn("full_frames", full_frames, lambda: (params, frames_plus1()))

    @jax.jit
    def full_frames_bf16(p, fr):
        return R.raft_forward_frames(p, fr, dtype=jnp.bfloat16)

    time_fn("full_frames_bf16", full_frames_bf16, lambda: (params, frames_plus1()))


if __name__ == "__main__":
    main()
