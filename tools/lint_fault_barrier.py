#!/usr/bin/env python
"""Lint: broad exception catches may exist only at declared fault barriers.

The reliability subsystem (``video_features_tpu/reliability``) only works if
failures reach the per-video barrier *classified* — every new
``except Exception`` that swallows or blurs an error erodes the taxonomy back
into the reference's print-and-continue. This check (run as a tier-1 test,
``tests/test_fault_barrier_lint.py``) enforces two rules over
``video_features_tpu/``:

1. every ``except Exception`` / ``except BaseException`` / bare ``except:``
   line must carry a ``fault-barrier:`` comment stating why the broad catch
   is legitimate there;
2. the per-file site counts must match the declared allowlist below — adding
   a new barrier is a deliberate act that edits this file, not a drive-by.

Usage: ``python tools/lint_fault_barrier.py [repo_root]`` → exit 0 clean,
1 with findings on stderr.
"""

from __future__ import annotations

import os
import re
import sys
from typing import Dict, List, Tuple

# Declared barriers: package-relative posix path -> expected broad-catch count.
ALLOWED: Dict[str, int] = {
    "video_features_tpu/extractors/base.py": 3,    # per-video fault barrier + its async-write reap arm + unwind-path write accounting
    "video_features_tpu/extractors/flow.py": 3,    # async-copy + imshow probes + precompile warmup
    "video_features_tpu/io/output.py": 1,          # writer thread: error stored on the WriteHandle
    "video_features_tpu/parallel/pipeline.py": 2,  # distributed-client probe + worker re-raise
    "video_features_tpu/reliability/retry.py": 2,  # classified re-raise + attempts attr
    "video_features_tpu/reliability/watchdog.py": 1,  # hands the exception to the waiter
    "video_features_tpu/run.py": 1,                # best-effort JAX_PLATFORMS shim
}

MARKER = "fault-barrier:"
BROAD = re.compile(r"^\s*except\s*(\(\s*)?(Base)?Exception\b|^\s*except\s*:")


def scan(repo_root: str) -> Tuple[List[str], Dict[str, int]]:
    """(findings, per-file broad-catch counts) for the package tree."""
    findings: List[str] = []
    counts: Dict[str, int] = {}
    pkg = os.path.join(repo_root, "video_features_tpu")
    for dirpath, _dirnames, filenames in os.walk(pkg):
        for name in sorted(filenames):
            if not name.endswith(".py"):
                continue
            path = os.path.join(dirpath, name)
            rel = os.path.relpath(path, repo_root).replace(os.sep, "/")
            with open(path, encoding="utf-8") as f:
                for lineno, line in enumerate(f, start=1):
                    if not BROAD.match(line):
                        continue
                    counts[rel] = counts.get(rel, 0) + 1
                    if MARKER not in line:
                        findings.append(
                            f"{rel}:{lineno}: broad except without a "
                            f"'{MARKER}' justification comment — raise a "
                            "classified reliability error instead, or declare "
                            "the barrier"
                        )
    for rel, n in sorted(counts.items()):
        want = ALLOWED.get(rel)
        if want is None:
            findings.append(
                f"{rel}: {n} broad except(s) in a file with no declared "
                "barriers — new broad catches must be added to "
                "tools/lint_fault_barrier.py ALLOWED deliberately"
            )
        elif n != want:
            findings.append(
                f"{rel}: expected {want} declared barrier(s), found {n} — "
                "update tools/lint_fault_barrier.py ALLOWED if intentional"
            )
    for rel, want in sorted(ALLOWED.items()):
        if rel not in counts and os.path.exists(os.path.join(repo_root, rel)):
            findings.append(
                f"{rel}: allowlist expects {want} barrier(s) but none found — "
                "prune the stale ALLOWED entry"
            )
    return findings, counts


def main(argv=None) -> int:
    args = list(sys.argv[1:] if argv is None else argv)
    repo_root = args[0] if args else os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    findings, counts = scan(repo_root)
    if findings:
        for f in findings:
            print(f, file=sys.stderr)
        return 1
    print(f"fault-barrier lint: {sum(counts.values())} declared barrier(s) "
          f"across {len(counts)} file(s); no strays")
    return 0


if __name__ == "__main__":
    sys.exit(main())
