#!/usr/bin/env python
"""Shim: the fault-barrier lint now lives in the vftlint framework.

The PR-1 standalone lint migrated to
``tools/vftlint/rules/fault_barrier.py`` when the AST framework landed;
this entry point keeps the original contract byte-for-byte —
``python tools/lint_fault_barrier.py [repo_root]`` → exit 0 clean, 1 with
findings on stderr — and re-exports ``scan``/``ALLOWED``/``MARKER``/``BROAD``
for ``tests/test_fault_barrier_lint.py``. Run the full rule suite with
``python -m tools.vftlint`` instead.
"""

from __future__ import annotations

import os
import sys

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO_ROOT not in sys.path:
    sys.path.insert(0, _REPO_ROOT)

from tools.vftlint.rules.fault_barrier import (  # noqa: E402,F401
    ALLOWED,
    BROAD,
    MARKER,
    scan,
)


def main(argv=None) -> int:
    args = list(sys.argv[1:] if argv is None else argv)
    repo_root = args[0] if args else _REPO_ROOT
    findings, counts = scan(repo_root)
    if findings:
        for f in findings:
            print(f, file=sys.stderr)
        return 1
    print(f"fault-barrier lint: {sum(counts.values())} declared barrier(s) "
          f"across {len(counts)} file(s); no strays")
    return 0


if __name__ == "__main__":
    sys.exit(main())
