"""Lock model: discovery, naming, scopes, and the acquisition graph.

The serving stack holds 9 locks across 8 modules; three recurring review
findings — lock-order inversions, shared state touched off-lock, blocking
work under a lock — are exactly the hazards the test suite cannot observe
(a deadlock needs the losing interleaving; a lost ``+=`` loses once a year).
This module builds the shared analysis the three lock-discipline rules
consume (``lock-order``, ``guarded-by``, ``blocking-under-lock``):

1. **discovery**: every ``threading.Lock/RLock/Condition()`` creation site,
   found by AST shape — ``self._x = threading.Lock()`` inside a class, a
   module-level ``_x = threading.Lock()``, or a dict-literal value
   (``slot = {"lock": threading.Lock(), ...}``). Each site gets a stable
   canonical id ``<rel>:<Class>.<attr>`` (or ``<rel>:<name>`` /
   ``<rel>:<target>['<key>']``) and a friendly name via :data:`LOCK_NAMES`
   — the "how we name locks" registry (docs/static-analysis.md).
2. **scopes**: per function, a structural walk resolves ``with <lock>:``
   blocks (and bare ``<lock>.acquire()`` calls) to discovered locks and
   tracks the held set statement by statement. Nested ``def``/``lambda``
   bodies are NOT under the enclosing lock at runtime and are scanned as
   their own scopes.
3. **the graph**: direct intra-package calls are resolved name-based, the
   same trade :mod:`.tracing` makes — ``self.m()`` to the enclosing class's
   methods, ``self.attr.m()`` / ``name.m()`` through an attribute→class map
   (inferred from ``<x>.<attr> = ClassName(...)`` assignments, seeded by
   :data:`ATTR_TYPE_SEEDS` for the wirings assignment inference cannot see),
   bare names to module-level package functions. A fixpoint then yields each
   function's MAY-acquire lock set and MAY-reach blocking sinks, so
   ``with self._lock: self.queue.submit(...)`` produces the interprocedural
   service→queue edge (and would surface a file write three calls down).
   Unresolvable calls are silently not followed — the analysis under-
   approximates through indirection, and the rules exist to keep the hot
   lock scopes direct enough to analyze.

:class:`LockOrderWatch` is the runtime cross-check: a test-only shim
wrapping the named locks that asserts the declared ``LOCK_ORDER`` while the
daemon tests actually run, so the static table and reality cannot drift
silently (tests/test_service.py, tests/test_multimodel.py).
"""

from __future__ import annotations

import ast
import threading
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

from .tracing import dotted_name

# callee class names that create a lock (matched on the last dotted part, so
# both `threading.Lock()` and a `from threading import Lock` spelling count)
LOCK_CLASSES = {"Lock", "RLock", "Condition"}

# canonical creation site -> friendly name. Every lock that participates in
# nesting must be named here (lock-order findings use these names, LOCK_ORDER
# declares them, and LockOrderWatch wraps them at runtime). A lock missing
# from this table keeps its canonical id as its name — fine for leaf locks,
# but the lock-order rule insists on a declared name + order position the
# moment it shows up in a nested acquisition.
LOCK_NAMES: Dict[str, str] = {
    "video_features_tpu/serve/daemon.py:ExtractionService._lock": "service",
    "video_features_tpu/serve/scheduler.py:RequestQueue._lock": "queue",
    "video_features_tpu/serve/wal.py:AdmissionLog._lock": "wal",
    "video_features_tpu/obs/metrics.py:MetricsRegistry._lock": "registry",
    "video_features_tpu/obs/journal.py:SpanJournal._lock": "journal",
    "video_features_tpu/utils/metrics.py:StageClock._lock": "clock",
    "video_features_tpu/parallel/pipeline.py:DecodePrefetcher._resize_lock":
        "resize",
    "video_features_tpu/parallel/pipeline.py:slot['lock']": "slot",
    "video_features_tpu/extractors/flow.py:ExtractFlow._precompile_lock":
        "precompile",
    "video_features_tpu/extractors/flow.py:ExtractFlow._frames_steps_lock":
        "flow-steps",
    "video_features_tpu/reliability/faults.py:_lock": "faults",
}

# attribute -> owning class, for the cross-module wirings that assignment
# inference cannot type (`self.journal = extractor._journal` carries no
# constructor). Inference from `<x>.<attr> = ClassName(...)` assignments
# covers the rest (queue -> RequestQueue, breaker -> TenantBreaker, ...).
ATTR_TYPE_SEEDS: Dict[str, str] = {
    "journal": "SpanJournal",
    "_journal": "SpanJournal",
    "metrics": "MetricsRegistry",
    "_metrics": "MetricsRegistry",
    "_registry": "MetricsRegistry",
}

# ---------------------------------------------------------------------------
# blocking sinks (syntactic): the blocking-under-lock rule's leaf set.
# Matching is deliberately name-shaped, like the rest of vftlint: `open`
# covers file I/O at its chokepoint (reads/writes happen on handles a lock
# scope should never have opened), queue put/get count only on queue-ish
# receivers so `dict.get` stays out, and `*_nowait` / `block=False` forms
# are exempt by construction.

_BLOCKING_DOTTED = {
    "time.sleep",
    "subprocess.run", "subprocess.Popen", "subprocess.call",
    "subprocess.check_call", "subprocess.check_output",
    "os.makedirs", "os.replace", "os.rename", "os.remove", "os.unlink",
    "os.system",
    "shutil.rmtree", "shutil.copyfile", "shutil.copy", "shutil.move",
    "json.dump",  # dump writes a file; dumps is pure and not listed
    "socket.create_connection",
}
_BLOCKING_BARE = {"open", "print", "input"}
_SOCKET_METHODS = {"recv", "recvfrom", "sendall", "accept", "connect",
                   "listen"}
_DEVICE_SYNC_METHODS = {"_wait", "block_until_ready"}
_QUEUE_METHODS = {"put", "get"}
_QUEUEISH = {"q", "_q", "queue", "_queue", "inq", "outq"}


def _receiver_token(node: ast.AST) -> Optional[str]:
    """The last name component of a call receiver (`self._q` -> '_q',
    `slot["q"]` -> 'q') for the queue-ish heuristic."""
    if isinstance(node, ast.Attribute):
        return node.attr
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Subscript):
        key = node.slice
        if isinstance(key, ast.Constant) and isinstance(key.value, str):
            return key.value
    return None


def _queueish(node: ast.AST) -> bool:
    token = _receiver_token(node)
    if token is None:
        return False
    token = token.lower()
    return (token in _QUEUEISH or token.endswith("_q")
            or token.endswith("queue"))


def classify_sink(call: ast.Call) -> Optional[str]:
    """A human-readable sink description when ``call`` may block, else None."""
    name = dotted_name(call.func) or ""
    if name in _BLOCKING_DOTTED:
        return f"{name}()"
    if isinstance(call.func, ast.Name) and name in _BLOCKING_BARE:
        return f"{name}() [I/O]" if name == "open" else f"{name}()"
    if not isinstance(call.func, ast.Attribute):
        return None
    attr = call.func.attr
    if attr in _DEVICE_SYNC_METHODS:
        return f".{attr}() [device sync]"
    if attr in _SOCKET_METHODS:
        return f".{attr}() [socket]"
    if attr == "wait" and not isinstance(call.func.value, ast.Constant):
        return ".wait()"
    if attr == "join":
        token = (_receiver_token(call.func.value) or "").lower()
        if "thread" in token or "proc" in token:
            return ".join() [thread]"
    if attr in _QUEUE_METHODS and _queueish(call.func.value):
        for kw in call.keywords:
            if (kw.arg == "block" and isinstance(kw.value, ast.Constant)
                    and kw.value.value is False):
                return None
        return f"queue .{attr}()"
    return None


# ---------------------------------------------------------------------------
# discovery + per-function summaries


class LockSite:
    """One discovered lock creation site."""

    __slots__ = ("canonical", "name", "rel", "line", "kind", "cls", "attr",
                 "form")

    def __init__(self, canonical: str, rel: str, line: int, kind: str,
                 cls: Optional[str], attr: str, form: str):
        self.canonical = canonical
        self.name = LOCK_NAMES.get(canonical, canonical)
        self.rel = rel
        self.line = line
        self.kind = kind  # Lock | RLock | Condition
        self.cls = cls
        self.attr = attr
        self.form = form  # attr | global | dictkey

    @property
    def reentrant(self) -> bool:
        return self.kind == "RLock"


class FnSummary:
    """One function's lock-relevant facts (events carry the held set)."""

    __slots__ = ("rel", "cls", "name", "line", "node", "qual",
                 "acquire_events", "call_events", "sink_events", "all_calls",
                 "events")

    def __init__(self, rel: str, cls: Optional[str], name: str, line: int,
                 node: ast.AST):
        self.rel = rel
        self.cls = cls
        self.name = name
        self.line = line
        self.node = node
        self.qual = f"{cls}.{name}" if cls else name
        # (lock name, line, held-before tuple)
        self.acquire_events: List[Tuple[str, int, Tuple[str, ...]]] = []
        # (Call node, line, held tuple) — only calls made while >=1 lock held
        self.call_events: List[Tuple[ast.Call, int, Tuple[str, ...]]] = []
        # (sink description, line, held tuple) — every direct sink
        self.sink_events: List[Tuple[str, int, Tuple[str, ...]]] = []
        self.all_calls: List[ast.Call] = []
        # ("stmt" | "expr", node, held tuple) — guarded-by consumes these
        self.events: List[Tuple[str, ast.AST, Tuple[str, ...]]] = []


def _is_lock_call(node: ast.AST) -> Optional[str]:
    if not isinstance(node, ast.Call):
        return None
    name = dotted_name(node.func)
    if name is None:
        return None
    last = name.rsplit(".", 1)[-1]
    return last if last in LOCK_CLASSES else None


def _walk_no_defs(node: ast.AST) -> Iterable[ast.AST]:
    """ast.walk that does not descend into nested def/lambda/class bodies
    (they execute later, outside the enclosing lock scope)."""
    stack = [node]
    while stack:
        cur = stack.pop()
        yield cur
        for child in ast.iter_child_nodes(cur):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.Lambda, ast.ClassDef)):
                continue
            stack.append(child)


class LockModel:
    """The package-wide lock model (built once per lint run)."""

    def __init__(self, root: str, sources: Dict[str, "object"],
                 package_prefix: str = "video_features_tpu/"):
        self.root = root
        self.sites: List[LockSite] = []
        # resolution indexes
        self._by_cls_attr: Dict[Tuple[str, Optional[str], str], LockSite] = {}
        self._by_attr: Dict[Tuple[str, str], List[LockSite]] = {}
        self._by_global: Dict[Tuple[str, str], LockSite] = {}
        self._by_dictkey: Dict[Tuple[str, str], List[LockSite]] = {}
        self._by_name: Dict[str, LockSite] = {}
        # call resolution indexes
        self._module_funcs: Dict[str, List[FnSummary]] = {}
        self._methods: Dict[Tuple[str, str], List[FnSummary]] = {}
        self._attr_types: Dict[str, Set[str]] = {
            k: {v} for k, v in ATTR_TYPE_SEEDS.items()}
        self._class_names: Set[str] = set()
        self.functions: List[FnSummary] = []
        self._fns_by_rel: Dict[str, List[FnSummary]] = {}

        trees = [(rel, src.tree) for rel, src in sorted(sources.items())
                 if rel.startswith(package_prefix)
                 and getattr(src, "tree", None) is not None]
        for rel, tree in trees:
            self._discover_locks(rel, tree)
            self._index_classes(tree)
        for rel, tree in trees:
            self._infer_attr_types(tree)
        for rel, tree in trees:
            self._scan_functions(rel, tree)
        self._fixpoint()

    # -- discovery ----------------------------------------------------------

    def _discover_locks(self, rel: str, tree: ast.AST) -> None:
        def visit(node: ast.AST, cls: Optional[str], fn: Optional[str]):
            for child in ast.iter_child_nodes(node):
                if isinstance(child, ast.ClassDef):
                    visit(child, child.name, fn)
                    continue
                if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    visit(child, cls, child.name)
                    continue
                if isinstance(child, (ast.Assign, ast.AnnAssign)):
                    targets = (child.targets if isinstance(child, ast.Assign)
                               else [child.target])
                    value = child.value
                    kind = _is_lock_call(value)
                    if kind:
                        for t in targets:
                            self._register(rel, t, kind, child.lineno, cls)
                    elif isinstance(value, ast.Dict):
                        base = (targets[0].id if targets and
                                isinstance(targets[0], ast.Name) else None)
                        for k, v in zip(value.keys, value.values):
                            kd = _is_lock_call(v)
                            if (kd and base and isinstance(k, ast.Constant)
                                    and isinstance(k.value, str)):
                                self._register_site(LockSite(
                                    f"{rel}:{base}[{k.value!r}]", rel,
                                    v.lineno, kd, None, k.value, "dictkey"))
                visit(child, cls, fn)

        visit(tree, None, None)

    def _register(self, rel: str, target: ast.AST, kind: str, line: int,
                  cls: Optional[str]) -> None:
        if (isinstance(target, ast.Attribute)
                and isinstance(target.value, ast.Name)
                and target.value.id == "self" and cls):
            self._register_site(LockSite(
                f"{rel}:{cls}.{target.attr}", rel, line, kind, cls,
                target.attr, "attr"))
        elif isinstance(target, ast.Name):
            self._register_site(LockSite(
                f"{rel}:{target.id}", rel, line, kind, None, target.id,
                "global"))

    def _register_site(self, site: LockSite) -> None:
        if site.canonical in {s.canonical for s in self.sites}:
            return
        self.sites.append(site)
        self._by_name.setdefault(site.name, site)
        if site.form == "attr":
            self._by_cls_attr[(site.rel, site.cls, site.attr)] = site
            self._by_attr.setdefault((site.rel, site.attr), []).append(site)
        elif site.form == "global":
            self._by_global[(site.rel, site.attr)] = site
        else:
            self._by_dictkey.setdefault((site.rel, site.attr), []).append(site)

    def site_named(self, name: str) -> Optional[LockSite]:
        return self._by_name.get(name)

    def sites_in(self, rel: str) -> List[LockSite]:
        return [s for s in self.sites if s.rel == rel]

    # -- class / attr-type indexing -----------------------------------------

    def _index_classes(self, tree: ast.AST) -> None:
        for node in ast.walk(tree):
            if isinstance(node, ast.ClassDef):
                self._class_names.add(node.name)

    def _infer_attr_types(self, tree: ast.AST) -> None:
        """`<x>.<attr> = ClassName(...)` types attr as ClassName for call
        resolution (`self.queue = RequestQueue(...)` -> queue.submit
        resolves into RequestQueue). Collisions widen the scan — the safe
        direction for a linter."""
        for node in ast.walk(tree):
            if not isinstance(node, ast.Assign):
                continue
            if not isinstance(node.value, ast.Call):
                continue
            cname = (dotted_name(node.value.func) or "").rsplit(".", 1)[-1]
            if cname not in self._class_names:
                continue
            for t in node.targets:
                if isinstance(t, ast.Attribute):
                    self._attr_types.setdefault(t.attr, set()).add(cname)

    # -- lock expression resolution ------------------------------------------

    def resolve_lock_expr(self, expr: ast.AST, rel: str,
                          cls: Optional[str]) -> Optional[str]:
        """The lock NAME a `with <expr>:` / `<expr>.acquire()` holds, or
        None when the expression is not a discovered lock."""
        if isinstance(expr, ast.Attribute):
            if isinstance(expr.value, ast.Name) and expr.value.id == "self":
                site = self._by_cls_attr.get((rel, cls, expr.attr))
                if site is None:
                    candidates = self._by_attr.get((rel, expr.attr), [])
                    site = candidates[0] if len(candidates) == 1 else None
                return site.name if site else None
            return None
        if isinstance(expr, ast.Name):
            site = self._by_global.get((rel, expr.id))
            return site.name if site else None
        if isinstance(expr, ast.Subscript):
            key = expr.slice
            if isinstance(key, ast.Constant) and isinstance(key.value, str):
                candidates = self._by_dictkey.get((rel, key.value), [])
                if len(candidates) == 1:
                    return candidates[0].name
        return None

    # -- function scanning ----------------------------------------------------

    def _scan_functions(self, rel: str, tree: ast.AST) -> None:
        def visit(node: ast.AST, cls: Optional[str]):
            for child in ast.iter_child_nodes(node):
                if isinstance(child, ast.ClassDef):
                    visit(child, child.name)
                elif isinstance(child, (ast.FunctionDef,
                                        ast.AsyncFunctionDef)):
                    self._scan_fn(rel, cls, child)
                    visit(child, cls)  # nested defs: their own scopes
                else:
                    visit(child, cls)

        visit(tree, None)

    def _scan_fn(self, rel: str, cls: Optional[str], fn) -> None:
        s = FnSummary(rel, cls, fn.name, fn.lineno, fn)
        self._block(fn.body, frozenset(), s)
        self.functions.append(s)
        self._fns_by_rel.setdefault(rel, []).append(s)
        if cls:
            self._methods.setdefault((cls, fn.name), []).append(s)
        else:
            self._module_funcs.setdefault(fn.name, []).append(s)

    def _block(self, stmts, held: frozenset, s: FnSummary) -> None:
        for st in stmts:
            if isinstance(st, (ast.FunctionDef, ast.AsyncFunctionDef,
                               ast.ClassDef)):
                continue  # separate runtime scope
            if isinstance(st, (ast.With, ast.AsyncWith)):
                h = set(held)
                for item in st.items:
                    lock = self.resolve_lock_expr(item.context_expr, s.rel,
                                                  s.cls)
                    if lock is not None:
                        s.acquire_events.append(
                            (lock, item.context_expr.lineno,
                             tuple(sorted(h))))
                        h.add(lock)
                    else:
                        self._exprs(item.context_expr, frozenset(h), s)
                self._block(st.body, frozenset(h), s)
            elif isinstance(st, ast.If):
                self._exprs(st.test, held, s)
                self._block(st.body, held, s)
                self._block(st.orelse, held, s)
            elif isinstance(st, (ast.For, ast.AsyncFor)):
                self._exprs(st.iter, held, s)
                self._block(st.body, held, s)
                self._block(st.orelse, held, s)
            elif isinstance(st, ast.While):
                self._exprs(st.test, held, s)
                self._block(st.body, held, s)
                self._block(st.orelse, held, s)
            elif isinstance(st, ast.Try):
                self._block(st.body, held, s)
                for handler in st.handlers:
                    self._block(handler.body, held, s)
                self._block(st.orelse, held, s)
                self._block(st.finalbody, held, s)
            else:
                self._exprs(st, held, s)

    def _exprs(self, node: ast.AST, held: frozenset, s: FnSummary) -> None:
        held_t = tuple(sorted(held))
        s.events.append(("stmt", node, held_t))
        for sub in _walk_no_defs(node):
            if not isinstance(sub, ast.Call):
                continue
            s.all_calls.append(sub)
            # `lock.acquire()` outside a with-statement is an acquisition
            # for edge purposes (held-until-unknown; the repo uses `with`
            # for every real lock, fixtures may not)
            if (isinstance(sub.func, ast.Attribute)
                    and sub.func.attr == "acquire"):
                lock = self.resolve_lock_expr(sub.func.value, s.rel, s.cls)
                if lock is not None:
                    s.acquire_events.append((lock, sub.lineno, held_t))
                    continue
            desc = classify_sink(sub)
            if desc is not None:
                s.sink_events.append((desc, sub.lineno, held_t))
            elif held:
                s.call_events.append((sub, sub.lineno, held_t))

    # -- call resolution + fixpoint -------------------------------------------

    def resolve_call(self, call: ast.Call, fn: FnSummary) -> List[FnSummary]:
        func = call.func
        if isinstance(func, ast.Name):
            return self._module_funcs.get(func.id, [])
        if not isinstance(func, ast.Attribute):
            return []
        base = func.value
        if isinstance(base, ast.Name) and base.id == "self":
            if fn.cls is None:
                return []
            return self._methods.get((fn.cls, func.attr), [])
        recv_attr = None
        if isinstance(base, ast.Attribute):
            recv_attr = base.attr  # self.queue.submit -> 'queue'
        elif isinstance(base, ast.Name):
            recv_attr = base.id  # clock.stage -> 'clock'
        if recv_attr is None:
            return []
        out: List[FnSummary] = []
        for cname in self._attr_types.get(recv_attr, ()):
            out.extend(self._methods.get((cname, func.attr), []))
        return out

    def _fixpoint(self) -> None:
        """Transitive MAY-acquire locks and MAY-reach blocking sinks."""
        self._callees: Dict[int, List[FnSummary]] = {}
        for fn in self.functions:
            callees: List[FnSummary] = []
            for call in fn.all_calls:
                callees.extend(self.resolve_call(call, fn))
            self._callees[id(fn)] = callees
        self.eff_locks: Dict[int, Set[str]] = {
            id(fn): {l for l, _, _ in fn.acquire_events}
            for fn in self.functions}
        # sink -> shortest discovered via-chain of function quals
        self.eff_sinks: Dict[int, Dict[str, Tuple[str, ...]]] = {
            id(fn): {desc: () for desc, _, _ in fn.sink_events}
            for fn in self.functions}
        changed = True
        while changed:
            changed = False
            for fn in self.functions:
                locks = self.eff_locks[id(fn)]
                sinks = self.eff_sinks[id(fn)]
                for callee in self._callees[id(fn)]:
                    for lock in self.eff_locks[id(callee)]:
                        if lock not in locks:
                            locks.add(lock)
                            changed = True
                    for desc, chain in self.eff_sinks[id(callee)].items():
                        new_chain = (callee.qual,) + chain
                        if len(new_chain) > 4:
                            new_chain = new_chain[:4]
                        if (desc not in sinks
                                or len(new_chain) < len(sinks[desc])):
                            if sinks.get(desc) != new_chain:
                                sinks[desc] = new_chain
                                changed = True

    # -- rule-facing queries ---------------------------------------------------

    def functions_in(self, rel: str) -> List[FnSummary]:
        return self._fns_by_rel.get(rel, [])

    def callees(self, fn: FnSummary) -> List[FnSummary]:
        return self._callees.get(id(fn), [])

    def call_effect_locks(self, call: ast.Call,
                          fn: FnSummary) -> Dict[str, str]:
        """lock name -> callee qual that (transitively) acquires it."""
        out: Dict[str, str] = {}
        for callee in self.resolve_call(call, fn):
            for lock in self.eff_locks[id(callee)]:
                out.setdefault(lock, callee.qual)
        return out

    def call_effect_sinks(self, call: ast.Call,
                          fn: FnSummary) -> Dict[str, Tuple[str, ...]]:
        """sink description -> via-chain of function quals."""
        out: Dict[str, Tuple[str, ...]] = {}
        for callee in self.resolve_call(call, fn):
            for desc, chain in self.eff_sinks[id(callee)].items():
                full = (callee.qual,) + chain
                if desc not in out or len(full) < len(out[desc]):
                    out[desc] = full
        return out

    def is_reentrant(self, name: str) -> bool:
        site = self._by_name.get(name)
        return site is not None and site.reentrant


def shared_model(root: str, sources: Dict[str, object],
                 shared: Dict[str, object]) -> LockModel:
    """The per-run lock model (built once, shared by all three lock rules
    via run_lint's ``shared`` dict — the parse-once discipline)."""
    model = shared.get("lock-model")
    if model is None:
        model = LockModel(root, sources)
        shared["lock-model"] = model
    return model


# ---------------------------------------------------------------------------
# runtime cross-check (test-only)


class LockOrderWatch:
    """Assert the declared LOCK_ORDER on live locks during daemon tests.

    ``instrument_service`` swaps the named locks of an ``ExtractionService``
    (service/queue/registry/clock/journal) for recording proxies; every
    acquisition checks the acquiring thread's held stack against the
    declared order. Violations are recorded (and asserted empty by the test
    teardown), observed (outer, inner) pairs land in ``edges`` so tests can
    also prove the instrumentation saw real nesting. Reentrant
    re-acquisition of the same lock is not an edge (the service lock is an
    RLock).
    """

    def __init__(self, order: Sequence[str]):
        self._rank = {name: i for i, name in enumerate(order)}
        self._held = threading.local()
        self.violations: List[str] = []
        self.edges: Set[Tuple[str, str]] = set()

    def wrap(self, lock, name: str) -> "_WatchedLock":
        return _WatchedLock(self, lock, name)

    def instrument_service(self, service) -> "LockOrderWatch":
        service._lock = self.wrap(service._lock, "service")
        service.queue._lock = self.wrap(service.queue._lock, "queue")
        if getattr(service, "_wal", None) is not None:
            service._wal._lock = self.wrap(service._wal._lock, "wal")
        service.metrics._lock = self.wrap(service.metrics._lock, "registry")
        clock = service.ex.clock
        if clock is not None:
            clock._lock = self.wrap(clock._lock, "clock")
        if service.journal is not None:
            service.journal._lock = self.wrap(service.journal._lock,
                                              "journal")
        return self

    def _stack(self) -> List[str]:
        stack = getattr(self._held, "stack", None)
        if stack is None:
            stack = self._held.stack = []
        return stack

    def note_acquire(self, name: str) -> None:
        stack = self._stack()
        if name in stack:  # reentrant re-acquire: no new edge
            stack.append(name)
            return
        rank = self._rank.get(name)
        for held in stack:
            if (held, name) not in self.edges:
                self.edges.add((held, name))
            held_rank = self._rank.get(held)
            if (rank is not None and held_rank is not None
                    and held_rank > rank):
                self.violations.append(
                    f"acquired '{name}' while holding '{held}' — LOCK_ORDER "
                    f"declares '{name}' before '{held}'")
        stack.append(name)

    def note_release(self, name: str) -> None:
        stack = self._stack()
        for i in range(len(stack) - 1, -1, -1):
            if stack[i] == name:
                del stack[i]
                return

    def assert_clean(self) -> None:
        assert not self.violations, "\n".join(self.violations)


class _WatchedLock:
    """Proxy for one named lock: record order events, delegate the rest."""

    def __init__(self, watch: LockOrderWatch, lock, name: str):
        self._watch = watch
        self._lock = lock
        self.name = name

    def acquire(self, *args, **kwargs):
        self._watch.note_acquire(self.name)
        ok = self._lock.acquire(*args, **kwargs)
        if not ok:
            self._watch.note_release(self.name)
        return ok

    def release(self):
        self._lock.release()
        self._watch.note_release(self.name)

    def locked(self):
        return self._lock.locked()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc):
        self.release()
        return False
