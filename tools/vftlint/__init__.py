"""vftlint: AST-based static analysis for the video_features_tpu tree.

Pluggable rule framework (see :mod:`.core`) with shipped rules for the
invariants the test suite cannot observe: jit-purity, host-sync hygiene,
thread-shared-state discipline, explicit dtypes in the numeric core, the
fault-barrier allowlist (migrated from ``tools/lint_fault_barrier.py``), and
the test-tier fast registry.

CLI: ``python -m tools.vftlint [--rule ID] [root]`` — exit 0 clean, 1 with
findings, 2 on usage errors. Docs: docs/static-analysis.md.
"""

from .core import (  # noqa: F401
    Finding,
    Rule,
    SourceFile,
    all_rules,
    default_root,
    register,
    run_lint,
)
