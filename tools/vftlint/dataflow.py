"""Shared intraprocedural value-flow scaffolding for vftlint rules.

PR 11's host-sync rule proved a pattern: a *line-order* abstract
interpretation over one function body — branch-union at ``if``/``else``,
kill-on-reassign, nested ``def`` bodies seeded with the closure's state —
catches the device-boundary bugs a type checker can't see, without the cost
or fragility of a real fixpoint. This module generalizes that pass into
reusable pieces so the v3 rules (use-after-donate, recompile-hygiene,
wire-dtype, telemetry-schema) share one walker instead of four forks:

- :class:`LineOrderScanner` — the statement-structure walk extracted from
  host-sync's ``_TaintScanner`` (which now subclasses it). Subclasses own an
  arbitrary abstract state and implement ``snapshot``/``restore``/``merged``
  plus ``visit_expr`` (compound-statement heads) and ``visit_simple``
  (simple statements, including assignment transfer).
- :class:`StringFlow` — a concrete scanner resolving the *possible literal
  strings* a name can hold at each use point (``Constant``/``Name``/
  ``IfExp``/``or`` chains), used by telemetry-schema to resolve dynamic
  event-name arguments (e.g. the scheduler's ``_note_queued(job, event)``
  helper, whose call sites pass literals).
- :func:`walk_no_defs` — re-exported from :mod:`.locks`: expression walk
  that does not descend into nested ``def``/``lambda``/``class`` bodies
  (they execute later, in a different scope).

Single pass, no back-edge fixpoint, same deliberate limitation host-sync
documents: a fact born at the bottom of a loop body is not visible at its
top. Rules that care about loop back-edges (use-after-donate's re-staging
check) get explicit ``begin_loop``/``end_loop`` hooks instead.
"""

from __future__ import annotations

import ast
from typing import Callable, Dict, FrozenSet, Optional

from .locks import _walk_no_defs as walk_no_defs  # noqa: F401  (re-export)


class LineOrderScanner:
    """Line-order statement walk with branch-union state (see module doc).

    The contract host-sync's fixtures pin, now shared:

    - ``if``/``else``: each branch scans from the pre-branch state; the
      after-state is the union (a kill in one branch doesn't kill globally);
    - compound-statement heads (tests, iterables, with-items) are visited
      *before* their blocks — a block must see the state updates that scope
      it, never the stale pre-block state;
    - nested ``def``: scanned with a fork of the closure's current state,
      then the outer state is restored (closures see the enclosing facts,
      their own writes don't leak out);
    - ``class`` bodies inside functions are separate runtime scopes: skipped.
    """

    # -- state protocol (subclasses implement) ------------------------------

    def snapshot(self):
        raise NotImplementedError

    def restore(self, token) -> None:
        raise NotImplementedError

    def merged(self, tokens):
        raise NotImplementedError

    # -- visit hooks --------------------------------------------------------

    def visit_expr(self, expr: ast.AST) -> None:
        """A compound statement's head expression (if-test, for-iter,
        while-test, with-item), visited before the block it scopes."""

    def visit_simple(self, stmt: ast.stmt) -> None:
        """A simple statement — sink checks and assignment transfer."""

    def on_for(self, stmt) -> None:
        """Called after ``visit_expr(stmt.iter)``, before the body."""

    def begin_loop(self, stmt) -> None:
        """Entering a For/While body (use-after-donate's back-edge hook)."""

    def end_loop(self, stmt) -> None:
        """Leaving a For/While body (before the else-block)."""

    def scan_branch(self, body, stmt: ast.If, index: int) -> None:
        """One ``if`` arm (0 = body, 1 = orelse) — override to push
        branch-scoped context (wire-dtype's ``float32_wire`` gate)."""
        self.scan_block(body)

    def nested_def(self, stmt) -> None:
        token = self.snapshot()
        self.scan_block(stmt.body)
        self.restore(token)

    # -- the walk -----------------------------------------------------------

    def scan_block(self, stmts) -> None:
        for stmt in stmts:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self.nested_def(stmt)
            elif isinstance(stmt, ast.ClassDef):
                continue
            elif isinstance(stmt, ast.If):
                self.visit_expr(stmt.test)
                pre = self.snapshot()
                outs = []
                for index, branch in enumerate((stmt.body, stmt.orelse)):
                    self.restore(pre)
                    self.scan_branch(branch, stmt, index)
                    outs.append(self.snapshot())
                self.restore(self.merged(outs))
            elif isinstance(stmt, (ast.For, ast.AsyncFor)):
                self.visit_expr(stmt.iter)
                self.on_for(stmt)
                self.begin_loop(stmt)
                self.scan_block(stmt.body)
                self.end_loop(stmt)
                self.scan_block(stmt.orelse)
            elif isinstance(stmt, ast.While):
                self.visit_expr(stmt.test)
                self.begin_loop(stmt)
                self.scan_block(stmt.body)
                self.end_loop(stmt)
                self.scan_block(stmt.orelse)
            elif isinstance(stmt, (ast.With, ast.AsyncWith)):
                for item in stmt.items:
                    self.visit_expr(item.context_expr)
                self.scan_block(stmt.body)
            elif isinstance(stmt, ast.Try):
                self.scan_block(stmt.body)
                for handler in stmt.handlers:
                    self.scan_block(handler.body)
                self.scan_block(stmt.orelse)
                self.scan_block(stmt.finalbody)
            else:
                self.visit_simple(stmt)


# ---------------------------------------------------------------------------
# literal-string resolution

# env value: frozenset of possible strings, or None = unknown (TOP)
StrEnv = Dict[str, Optional[FrozenSet[str]]]


def literal_strings(expr: ast.AST, env: StrEnv) -> Optional[FrozenSet[str]]:
    """Possible literal-string values of ``expr`` under ``env``; None when
    any contributor is unresolvable (which makes the whole value unknown —
    a partial answer would let undocumented events hide behind one dynamic
    branch)."""
    if isinstance(expr, ast.Constant):
        return frozenset({expr.value}) if isinstance(expr.value, str) else None
    if isinstance(expr, ast.Name):
        return env.get(expr.id)
    if isinstance(expr, ast.IfExp):
        a = literal_strings(expr.body, env)
        b = literal_strings(expr.orelse, env)
        return a | b if a is not None and b is not None else None
    if isinstance(expr, ast.BoolOp) and isinstance(expr.op, ast.Or):
        out: FrozenSet[str] = frozenset()
        for v in expr.values:
            part = literal_strings(v, env)
            if part is None:
                return None
            out |= part
        return out
    return None


class StringFlow(LineOrderScanner):
    """Track name → possible-literal-strings through one function body and
    invoke ``on_call(call, env)`` for every call, in statement order with
    the environment live at that point."""

    def __init__(self, on_call: Callable[[ast.Call, StrEnv], None],
                 seed: Optional[StrEnv] = None):
        self.on_call = on_call
        self.env: StrEnv = dict(seed or {})

    def snapshot(self):
        return dict(self.env)

    def restore(self, token) -> None:
        self.env = dict(token)

    def merged(self, tokens):
        keys = set()
        for t in tokens:
            keys |= set(t)
        out: StrEnv = {}
        for k in keys:
            vals: FrozenSet[str] = frozenset()
            for t in tokens:
                v = t.get(k)
                if v is None:
                    vals = None  # type: ignore[assignment]
                    break
                vals |= v
            out[k] = vals
        return out

    def _calls(self, node: ast.AST) -> None:
        for sub in walk_no_defs(node):
            if isinstance(sub, ast.Call):
                self.on_call(sub, self.env)

    def visit_expr(self, expr: ast.AST) -> None:
        self._calls(expr)

    def visit_simple(self, stmt: ast.stmt) -> None:
        self._calls(stmt)
        if isinstance(stmt, ast.Assign):
            value = literal_strings(stmt.value, self.env)
            for target in stmt.targets:
                self._bind(target, value)
        elif isinstance(stmt, ast.AnnAssign) and stmt.value is not None:
            self._bind(stmt.target, literal_strings(stmt.value, self.env))
        elif isinstance(stmt, ast.AugAssign):
            self._bind(stmt.target, None)

    def _bind(self, target: ast.AST, value) -> None:
        if isinstance(target, ast.Name):
            self.env[target.id] = value
        elif isinstance(target, (ast.Tuple, ast.List)):
            for elt in target.elts:
                self._bind(elt, None)
        elif isinstance(target, ast.Starred):
            self._bind(target.value, None)


def scan_function_strings(fn, on_call: Callable[[ast.Call, StrEnv], None],
                          seed: Optional[StrEnv] = None) -> None:
    """Run a :class:`StringFlow` over one function body."""
    StringFlow(on_call, seed).scan_block(fn.body)
