"""vftlint core: AST sources, findings, the rule registry, and the runner.

The framework generalizes what ``tools/lint_fault_barrier.py`` proved on one
regex: correctness invariants the test suite cannot observe (a host sync is
slow, not wrong; a data race loses once a year) are enforced statically, with
*declared* escapes. Every suppression is an in-code annotation comment

    # <rule-id>: <reason>

on the finding line or the line directly above it — a reasonless annotation is
itself a finding, so the allowlist grammar cannot rot into blanket waivers.

Rules subclass :class:`Rule` and register with :func:`register`; the runner
(:func:`run_lint`) walks every selected rule's roots up front, parses each
source exactly once (:class:`SourceFile` objects are shared across rules, as
are the derived analyses — jit-traced-function discovery and the lock model —
via :meth:`SourceFile.traced` and the ``shared`` dict handed to
:meth:`Rule.prepare`), and returns findings formatted ``file:line rule-id
message``. CLI entry: ``python -m tools.vftlint``.
"""

from __future__ import annotations

import ast
import io
import os
import tokenize
from dataclasses import dataclass
from typing import Dict, Iterable, List, Optional, Sequence, Tuple, Type


@dataclass(frozen=True)
class Finding:
    """One diagnostic: ``path`` is repo-relative posix, ``line`` 1-based (0 =
    file-level / cross-file, e.g. an allowlist count mismatch)."""

    path: str
    line: int
    rule: str
    message: str

    def __str__(self) -> str:
        loc = f"{self.path}:{self.line}" if self.line else self.path
        return f"{loc} {self.rule} {self.message}"


class SourceFile:
    """A parsed module: AST + per-line comments (for annotation lookup)."""

    def __init__(self, root: str, rel: str):
        self.root = root
        self.rel = rel
        self.path = os.path.join(root, rel.replace("/", os.sep))
        with open(self.path, encoding="utf-8") as f:
            self.text = f.read()
        self.tree: Optional[ast.AST] = None
        self.parse_error: Optional[SyntaxError] = None
        try:
            self.tree = ast.parse(self.text, filename=self.rel)
        except SyntaxError as e:
            self.parse_error = e
        self._comments: Optional[Dict[int, str]] = None
        self._traced = None  # memoized tracing.traced_functions result
        # (rule_id, comment line) pairs a rule actually looked up this run —
        # the stale-suppression pass flags annotations nothing consumed
        self.consumed: set = set()

    @property
    def comments(self) -> Dict[int, str]:
        """Per-line comments, tokenized lazily: ``--changed`` mode only
        checks (and so only tokenizes) the files in the diff."""
        if self._comments is None:
            self._comments = {}
            try:
                for tok in tokenize.generate_tokens(
                        io.StringIO(self.text).readline):
                    if tok.type == tokenize.COMMENT:
                        # last comment on a line wins; at most one per line
                        self._comments[tok.start[0]] = tok.string
            except (tokenize.TokenError, IndentationError):
                pass  # the AST parse error already reports this file
        return self._comments

    def traced(self):
        """Memoized jit-traced FunctionDef discovery — jit-purity and
        host-sync both need it, and with 9+ rules the shared pass must not
        re-derive per consumer (tests/test_vftlint.py pins the budget)."""
        if self._traced is None:
            from .tracing import traced_functions

            self._traced = (traced_functions(self.tree)
                            if self.tree is not None else set())
        return self._traced

    def annotation(self, rule_id: str, line: int) -> Optional[str]:
        """Reason text of a ``# <rule-id>: <reason>`` annotation covering
        ``line`` (same line or the line above). None = not annotated;
        "" = annotated with an empty reason (invalid — callers report it)."""
        marker = rule_id + ":"
        for ln in (line, line - 1):
            comment = self.comments.get(ln)
            if comment is None or marker not in comment:
                continue
            self.consumed.add((rule_id, ln))
            return comment.split(marker, 1)[1].strip()
        return None


class Rule:
    """One invariant. Subclasses set ``id``/``title`` and implement
    :meth:`check_file` (per module) and/or :meth:`finalize` (cross-file,
    e.g. allowlist count reconciliation). ``roots`` limits the scan."""

    id: str = ""
    title: str = ""
    roots: Tuple[str, ...] = ("video_features_tpu",)

    def wants(self, rel: str) -> bool:
        return rel.endswith(".py")

    def prepare(self, root: str, sources: Dict[str, "SourceFile"],
                shared: Dict[str, object]) -> None:
        """Called once per run, after every selected rule's sources parsed.
        ``shared`` is a per-run scratch dict for analyses several rules
        consume (the lock-discipline rules build one lock model here)."""

    def check_file(self, src: SourceFile) -> Iterable[Finding]:
        return ()

    def finalize(self, root: str) -> Iterable[Finding]:
        return ()

    # -- shared helpers -----------------------------------------------------

    def annotation_live(self, src: SourceFile, line: int) -> bool:
        """Is the ``# <id>:`` annotation at comment ``line`` still backed by
        a would-be finding? Default: the rule looked it up this run (via
        :meth:`SourceFile.annotation`). Rules with their own annotation
        grammar (fault-barrier's line regex) override."""
        return (self.id, line) in src.consumed

    def suppressed(self, src: SourceFile, line: int,
                   extra: List[Finding]) -> bool:
        """True if an annotation with a non-empty reason covers ``line``.
        An empty-reason annotation appends its own finding to ``extra``."""
        reason = src.annotation(self.id, line)
        if reason is None:
            return False
        if not reason:
            extra.append(Finding(
                src.rel, line, self.id,
                f"'# {self.id}:' annotation has no reason — every "
                "suppression must say why it is legitimate"))
            return False
        return True


_REGISTRY: Dict[str, Rule] = {}


def register(cls: Type[Rule]) -> Type[Rule]:
    rule = cls()
    if not rule.id:
        raise ValueError(f"rule {cls.__name__} has no id")
    if rule.id in _REGISTRY:
        raise ValueError(f"duplicate rule id {rule.id!r}")
    _REGISTRY[rule.id] = rule
    return cls


def all_rules() -> Dict[str, Rule]:
    from . import rules  # noqa: F401 — importing registers the shipped rules

    return dict(_REGISTRY)


def _walk_py(root: str, sub: str) -> List[str]:
    base = os.path.join(root, sub.replace("/", os.sep))
    rels: List[str] = []
    if os.path.isfile(base):
        return [sub]
    for dirpath, dirnames, filenames in os.walk(base):
        dirnames[:] = [d for d in dirnames if d != "__pycache__"]
        for name in sorted(filenames):
            if name.endswith(".py"):
                rel = os.path.relpath(os.path.join(dirpath, name), root)
                rels.append(rel.replace(os.sep, "/"))
    return rels


def run_lint(root: str,
             rule_ids: Optional[Sequence[str]] = None,
             only: Optional[Iterable[str]] = None) -> List[Finding]:
    """Run the selected rules (default: all) over ``root``; findings sorted
    by file/line. Unknown rule ids raise KeyError (the CLI maps it to exit 2).

    ``only`` (repo-relative posix paths) is ``--changed`` mode: the full
    tree is still parsed and ``prepare()``d — the interprocedural rules
    (lock model, donation wiring, telemetry wrappers) need the whole
    package to judge one file — but per-file checks run only on the listed
    files, and findings are filtered to them. Cross-file ``finalize``
    reconciliation that depends on observations from *unchanged* files
    (e.g. a stale-declaration sweep) under-approximates here; the full run
    (CI's lint job) is the authority, ``--changed`` is the fast
    pre-commit loop."""
    registry = all_rules()
    if rule_ids:
        missing = [r for r in rule_ids if r not in registry]
        if missing:
            raise KeyError(
                f"unknown rule id(s) {missing}; known: {sorted(registry)}")
        rules = [registry[r] for r in rule_ids]
    else:
        rules = [registry[k] for k in sorted(registry)]

    # one shared parse pass: every file any selected rule wants is read and
    # parsed exactly once, THEN the rules run over the shared SourceFiles —
    # lint wall-clock stays O(files), not O(files × rules)
    sources: Dict[str, SourceFile] = {}
    per_rule_rels: List[Tuple[Rule, List[str]]] = []
    for rule in rules:
        rels: List[str] = []
        for sub in rule.roots:
            for rel in _walk_py(root, sub):
                if not rule.wants(rel):
                    continue
                rels.append(rel)
                if rel not in sources:
                    sources[rel] = SourceFile(root, rel)
        per_rule_rels.append((rule, rels))
    shared: Dict[str, object] = {}
    for rule in rules:
        rule.prepare(root, sources, shared)
    checked = None if only is None else set(only)
    findings: List[Finding] = []
    parse_reported = set()
    for rule, rels in per_rule_rels:
        for rel in rels:
            if checked is not None and rel not in checked:
                continue
            src = sources[rel]
            if src.parse_error is not None:
                if rel not in parse_reported:
                    parse_reported.add(rel)
                    findings.append(Finding(
                        rel, src.parse_error.lineno or 0, "parse-error",
                        f"cannot parse: {src.parse_error.msg}"))
                continue
            findings.extend(rule.check_file(src))
        findings.extend(rule.finalize(root))
    # stale-suppression reconciliation: an annotation comment no finding
    # consumed this run is dead weight — the same discipline stale lock
    # declarations already get (a suppression that outlives its violation
    # silently licenses the next one)
    for rule, rels in per_rule_rels:
        marker = rule.id + ":"
        for rel in rels:
            if checked is not None and rel not in checked:
                continue
            src = sources[rel]
            if src.parse_error is not None:
                continue
            for ln, comment in sorted(src.comments.items()):
                if marker not in comment:
                    continue
                if rule.annotation_live(src, ln):
                    continue
                findings.append(Finding(
                    rel, ln, rule.id,
                    f"stale '# {rule.id}:' suppression — nothing fires "
                    "here anymore; delete the comment (reconciliation, "
                    "same as stale lock declarations)"))
    if only is not None:
        allowed = set(only)
        findings = [f for f in findings if f.path in allowed]
    return sorted(findings, key=lambda f: (f.path, f.line, f.rule, f.message))


def collect_suppressions(
        root: str) -> List[Tuple[str, int, str, str]]:
    """Every in-code suppression annotation, as (rel, line, rule-id,
    reason), sorted. Scans exactly the files each registered rule scans, so
    an annotation outside a rule's roots (which that rule can never read)
    is not counted as a suppression."""
    registry = all_rules()
    comments_cache: Dict[str, Dict[int, str]] = {}

    def comments_of(rel: str) -> Dict[int, str]:
        if rel not in comments_cache:
            out: Dict[int, str] = {}
            path = os.path.join(root, rel.replace("/", os.sep))
            try:
                with open(path, encoding="utf-8") as f:
                    text = f.read()
                for tok in tokenize.generate_tokens(
                        io.StringIO(text).readline):
                    if tok.type == tokenize.COMMENT:
                        out[tok.start[0]] = tok.string
            except (OSError, tokenize.TokenError, IndentationError):
                pass
            comments_cache[rel] = out
        return comments_cache[rel]

    entries = set()
    for rule in registry.values():
        marker = rule.id + ":"
        for sub in rule.roots:
            for rel in _walk_py(root, sub):
                if not rule.wants(rel):
                    continue
                for ln, comment in comments_of(rel).items():
                    if marker in comment:
                        reason = comment.split(marker, 1)[1].strip()
                        entries.add((rel, ln, rule.id, reason))
    return sorted(entries)


def default_root() -> str:
    return os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))))
