"""CLI: ``python -m tools.vftlint [--rule ID ...] [--format F] [root]``."""

from __future__ import annotations

import argparse
import json
import sys

from .core import all_rules, default_root, run_lint


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m tools.vftlint",
        description="AST static analysis for video_features_tpu "
                    "(docs/static-analysis.md)")
    parser.add_argument("root", nargs="?", default=None,
                        help="repo root to scan (default: this checkout)")
    parser.add_argument("--rule", action="append", dest="rules", metavar="ID",
                        help="run only this rule (repeatable)")
    parser.add_argument("--list-rules", action="store_true",
                        help="list registered rules and exit")
    parser.add_argument("--format", choices=("text", "json", "github"),
                        default="text", dest="fmt",
                        help="finding output: text (default), json "
                             "(machine-readable array), github (workflow "
                             "::error annotations — findings show inline "
                             "on PRs)")
    try:
        args = parser.parse_args(argv)
    except SystemExit as e:
        return 2 if e.code else 0

    registry = all_rules()
    if args.list_rules:
        for rule_id in sorted(registry):
            print(f"{rule_id:22s} {registry[rule_id].title}")
        return 0

    root = args.root or default_root()
    try:
        findings = run_lint(root, args.rules)
    except KeyError as e:
        print(f"error: {e.args[0]}", file=sys.stderr)
        return 2
    if args.fmt == "json":
        print(json.dumps([
            {"file": f.path, "line": f.line, "rule": f.rule,
             "message": f.message,
             "suppression": f"# {f.rule}: <reason>"}
            for f in findings], indent=2))
    elif args.fmt == "github":
        for f in findings:
            # one annotation per finding; GitHub renders these inline on the
            # PR diff (docs/static-analysis.md). Newlines would break the
            # single-line command grammar — findings have none, but be safe.
            msg = f.message.replace("\n", " ")
            print(f"::error file={f.path},line={max(f.line, 1)},"
                  f"title=vftlint {f.rule}::{msg}")
    else:
        for finding in findings:
            print(finding)
    n_rules = len(args.rules) if args.rules else len(registry)
    if findings:
        print(f"vftlint: {len(findings)} finding(s) from {n_rules} rule(s)",
              file=sys.stderr)
        return 1
    if args.fmt == "text":
        print(f"vftlint: clean — {n_rules} rule(s) over {root}")
    else:
        print(f"vftlint: clean — {n_rules} rule(s) over {root}",
              file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
