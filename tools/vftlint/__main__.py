"""CLI: ``python -m tools.vftlint [--rule ID ...] [--format F] [--changed]
[--suppressions] [root]``."""

from __future__ import annotations

import argparse
import json
import subprocess
import sys
from typing import Optional, Set

from .core import all_rules, collect_suppressions, default_root, run_lint


def _changed_files(root: str, base: str) -> Optional[Set[str]]:
    """Repo-relative posix paths differing from ``base`` (committed or
    worktree) plus untracked files; None when ``root`` is not a git repo.
    Falls back base → main → HEAD so a fresh clone without an origin still
    lints its local edits."""

    def git(*args: str) -> subprocess.CompletedProcess:
        return subprocess.run(["git", "-C", root, *args],
                              capture_output=True, text=True)

    ref = None
    for candidate in (base, "main", "HEAD"):
        if git("rev-parse", "--verify", "--quiet",
               candidate).returncode == 0:
            ref = candidate
            break
    if ref is None:
        return None
    if ref != base:
        print(f"vftlint: base ref {base!r} not found, diffing against "
              f"{ref!r}", file=sys.stderr)
    files: Set[str] = set()
    for args in (("diff", "--name-only", ref),
                 ("ls-files", "--others", "--exclude-standard")):
        proc = git(*args)
        if proc.returncode == 0:
            files.update(line.strip() for line in proc.stdout.splitlines()
                         if line.strip())
    return files


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m tools.vftlint",
        description="AST static analysis for video_features_tpu "
                    "(docs/static-analysis.md)")
    parser.add_argument("root", nargs="?", default=None,
                        help="repo root to scan (default: this checkout)")
    parser.add_argument("--rule", action="append", dest="rules", metavar="ID",
                        help="run only this rule (repeatable)")
    parser.add_argument("--list-rules", action="store_true",
                        help="list registered rules and exit")
    parser.add_argument("--changed", action="store_true",
                        help="report findings only for files changed vs "
                             "--base (the whole tree is still analyzed — "
                             "the interprocedural rules need it); fast "
                             "pre-commit loop")
    parser.add_argument("--base", default="origin/main", metavar="REF",
                        help="git base ref for --changed (default: "
                             "origin/main, falling back to main, HEAD)")
    parser.add_argument("--suppressions", action="store_true",
                        help="print every in-code suppression annotation "
                             "(file:line rule-id reason) and exit — the "
                             "ledger docs/static-analysis.md mirrors")
    parser.add_argument("--format", choices=("text", "json", "github"),
                        default="text", dest="fmt",
                        help="finding output: text (default), json "
                             "(machine-readable array), github (workflow "
                             "::error annotations — findings show inline "
                             "on PRs)")
    try:
        args = parser.parse_args(argv)
    except SystemExit as e:
        return 2 if e.code else 0

    registry = all_rules()
    if args.list_rules:
        for rule_id in sorted(registry):
            print(f"{rule_id:22s} {registry[rule_id].title}")
        return 0

    root = args.root or default_root()

    if args.suppressions:
        entries = collect_suppressions(root)
        if args.fmt == "json":
            print(json.dumps([
                {"file": rel, "line": line, "rule": rule, "reason": reason}
                for rel, line, rule, reason in entries], indent=2))
        else:
            for rel, line, rule, reason in entries:
                print(f"{rel}:{line} {rule} {reason}")
        print(f"vftlint: {len(entries)} suppression(s)", file=sys.stderr)
        return 0

    only = None
    if args.changed:
        only = _changed_files(root, args.base)
        if only is None:
            print("vftlint: --changed needs a git checkout; linting "
                  "everything", file=sys.stderr)
        elif not only:
            print(f"vftlint: clean — no files changed vs {args.base}")
            return 0

    try:
        findings = run_lint(root, args.rules, only=only)
    except KeyError as e:
        print(f"error: {e.args[0]}", file=sys.stderr)
        return 2
    if args.fmt == "json":
        print(json.dumps([
            {"file": f.path, "line": f.line, "rule": f.rule,
             "message": f.message,
             "suppression": f"# {f.rule}: <reason>"}
            for f in findings], indent=2))
    elif args.fmt == "github":
        for f in findings:
            # one annotation per finding; GitHub renders these inline on the
            # PR diff (docs/static-analysis.md). Newlines would break the
            # single-line command grammar — findings have none, but be safe.
            msg = f.message.replace("\n", " ")
            print(f"::error file={f.path},line={max(f.line, 1)},"
                  f"title=vftlint {f.rule}::{msg}")
    else:
        for finding in findings:
            print(finding)
    n_rules = len(args.rules) if args.rules else len(registry)
    scope = f"{len(only)} changed file(s)" if only is not None else str(root)
    if findings:
        print(f"vftlint: {len(findings)} finding(s) from {n_rules} rule(s)",
              file=sys.stderr)
        return 1
    if args.fmt == "text":
        print(f"vftlint: clean — {n_rules} rule(s) over {scope}")
    else:
        print(f"vftlint: clean — {n_rules} rule(s) over {scope}",
              file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
