"""Shared AST helpers: dotted-name resolution and jit-traced-function discovery.

The jit-purity and host-sync rules both need the set of functions whose bodies
XLA traces. In this codebase a function becomes traced in one of three ways:

1. decorated with ``jax.jit`` / ``pjit`` / ``functools.partial(jax.jit, ...)``;
2. passed (first positional argument) to a jit wrapper call —
   ``jax.jit(fn)``, ``pjit(fn)``, ``shard_map(local, ...)``,
   ``sharded_apply(mesh, fn, ...)``, or any ``<obj>.jit(fn)`` (the extractors'
   ``self.runner.jit(step)``);
3. being a nested ``def`` inside an already-traced function (traced with it).

Detection is name-based, not dataflow-complete — a function smuggled through an
intermediate variable before wrapping escapes it. That trade is deliberate:
every wrap site in the tree names its function directly, and the rule exists to
keep it that way (a finding-free tree stays analyzable).
"""

from __future__ import annotations

import ast
from typing import Iterator, Optional, Set, Union

FunctionNode = Union[ast.FunctionDef, ast.AsyncFunctionDef]

# callee shapes that trace their function argument
_JIT_NAMES = {"jit", "pjit", "shard_map", "sharded_apply"}


def dotted_name(node: ast.AST) -> Optional[str]:
    """``a.b.c`` for Name/Attribute chains, else None."""
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def is_jit_wrapper(callee: ast.AST) -> bool:
    """Does calling ``callee`` with a function produce a traced function?"""
    name = dotted_name(callee)
    if name is None:
        return False
    last = name.rsplit(".", 1)[-1]
    return last in _JIT_NAMES


def _jit_decorated(fn: FunctionNode) -> bool:
    for dec in fn.decorator_list:
        if is_jit_wrapper(dec):
            return True
        # functools.partial(jax.jit, static_argnames=...)
        if isinstance(dec, ast.Call):
            if is_jit_wrapper(dec.func):
                return True
            if (dotted_name(dec.func) or "").rsplit(".", 1)[-1] == "partial":
                if dec.args and is_jit_wrapper(dec.args[0]):
                    return True
    return False


def traced_functions(tree: ast.AST) -> Set[FunctionNode]:
    """FunctionDef nodes whose bodies are traced by XLA (ways 1 and 2 above;
    callers handle 3 by walking the returned nodes' bodies whole)."""
    # index defs by name; names are near-unique per module here, and a
    # collision only widens the scan (safe direction for a linter)
    defs_by_name: dict = {}
    methods_by_name: dict = {}
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            defs_by_name.setdefault(node.name, []).append(node)
    for cls in ast.walk(tree):
        if isinstance(cls, ast.ClassDef):
            for node in cls.body:
                if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    methods_by_name.setdefault(node.name, []).append(node)

    traced: Set[FunctionNode] = set()
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            if _jit_decorated(node):
                traced.add(node)
        if not isinstance(node, ast.Call) or not is_jit_wrapper(node.func):
            continue
        # the function argument: first positional for <x>.jit/jit/pjit/
        # shard_map, second for sharded_apply(mesh, fn, ...)
        callee_last = (dotted_name(node.func) or "").rsplit(".", 1)[-1]
        arg_idx = 1 if callee_last == "sharded_apply" else 0
        if len(node.args) <= arg_idx:
            continue
        arg = node.args[arg_idx]
        if isinstance(arg, ast.Name):
            traced.update(defs_by_name.get(arg.id, ()))
        elif isinstance(arg, ast.Attribute):
            # self.runner.jit(self._forward) — resolve by method name
            traced.update(methods_by_name.get(arg.attr, ()))
    return traced


def walk_body(fn: FunctionNode) -> Iterator[ast.AST]:
    """Walk a traced function's body including nested defs (traced with it)."""
    for stmt in fn.body:
        yield from ast.walk(stmt)
