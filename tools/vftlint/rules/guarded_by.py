"""guarded-by: declared shared attributes are only touched under their lock.

PR 10's review caught ``StageClock`` losing ``+=`` increments (two threads,
no lock) and the registry iterating a dict the daemon mutates; both bug
classes are mechanical once the discipline is DECLARED. ``GUARDED_BY`` is
the promotion of the informal thread-shared-state prose into a checked map:
per multi-thread module, attribute site -> the name of the lock
(:data:`..locks.LOCK_NAMES`) that guards it. Any read or write of a
declared site — including iterating it, the snapshot-before-iterate class
of bug — must be lexically inside a ``with <that lock>:`` block.

Exemptions, in keeping with how the code is actually structured:

- ``__init__`` bodies (construction happens-before publication);
- functions whose name ends in ``_locked`` — the naming convention this
  repo uses for helpers whose CONTRACT is "caller holds the lock"
  (``RequestQueue._requeue_locked``); the suffix is the declaration, and
  the lock-order rule still sees the callers' ``with`` blocks;
- an explicit ``# guarded-by: <reason>`` annotation for deliberate
  off-lock access (e.g. a GIL-atomic monotone-counter read that tolerates
  an off-by-one-moment value).

Enforcement is per module: a guarded attribute read from ANOTHER module
goes through the owner's methods (or is a deliberate, documented dirty
read — the daemon's stats peeks). Stale declarations (a site no longer
touched anywhere in its module) are reported so the table cannot rot.
"""

from __future__ import annotations

import ast
import os
from typing import Dict, Iterable, List, Optional, Set, Tuple

from ..core import Finding, Rule, SourceFile, register
from .. import locks as locks_mod

# module -> {attribute site: lock name}. Site grammar mirrors the
# thread-shared-state table: `self.attr`, `<name>.attr`, `<name>['key']`,
# or a bare module-global name.
GUARDED_BY: Dict[str, Dict[str, str]] = {
    "video_features_tpu/serve/daemon.py": {
        "self._requests": "service",
        "self._jobs": "service",
        "self._done_sets": "service",
        "self._completed_requests": "service",
        "self._as_snapshot": "service",
        "self._publishing": "service",
    },
    "video_features_tpu/serve/wal.py": {
        "self._unresolved": "wal",
        "self._early_resolved": "wal",
        "self._degraded": "wal",
        "self._degraded_reason": "wal",
        "self._closed": "wal",
    },
    "video_features_tpu/serve/scheduler.py": {
        "self._tenants": "queue",
        "self._queued_paths": "queue",
        "self._vclock": "queue",
        "self._seq": "queue",
        "self._overrides": "queue",
        "self._default_weight": "queue",
        "self._default_quota": "queue",
        "t.heap": "queue",
        "t.vtime": "queue",
    },
    "video_features_tpu/obs/metrics.py": {
        "self._counters": "registry",
        "self._gauges": "registry",
        "self._hists": "registry",
    },
    "video_features_tpu/obs/journal.py": {
        "self.emitted": "journal",
        "self.dropped": "journal",
    },
    "video_features_tpu/utils/metrics.py": {
        "self.seconds": "clock",
        "self.counts": "clock",
        "self.units": "clock",
        "self.bytes": "clock",
    },
    "video_features_tpu/parallel/pipeline.py": {
        "slot['bytes']": "slot",
        "self._debt": "resize",
        # segmented-decode permit accounting + stats counters: written by
        # schedule()/workers, read by spare_permits()/segment_stats()
        "self._busy": "resize",
        "self._pending_baselines": "resize",
        "self._videos_segmented": "resize",
        "self._segments_decoded": "resize",
    },
    "video_features_tpu/extractors/flow.py": {
        "self._precompiled": "precompile",
        "self._frames_steps": "flow-steps",
    },
    "video_features_tpu/reliability/faults.py": {
        "_cached_spec": "faults",
        "_rules": "faults",
    },
}


def _site_of(node: ast.AST) -> Optional[str]:
    """Canonical site string for an attribute/subscript/name access whose
    base is a plain name, matching the GUARDED_BY grammar."""
    if isinstance(node, ast.Attribute) and isinstance(node.value, ast.Name):
        return f"{node.value.id}.{node.attr}"
    if isinstance(node, ast.Subscript) and isinstance(node.value, ast.Name):
        key = node.slice
        if isinstance(key, ast.Constant) and isinstance(key.value, str):
            return f"{node.value.id}[{key.value!r}]"
    if isinstance(node, ast.Name):
        return node.id
    return None


@register
class GuardedByRule(Rule):
    id = "guarded-by"
    title = "declared shared attributes accessed only under their lock"
    roots = ("video_features_tpu",)

    def __init__(self) -> None:
        self._model: Optional[locks_mod.LockModel] = None
        self._observed: Dict[str, Set[str]] = {}

    def prepare(self, root, sources, shared) -> None:
        self._model = locks_mod.shared_model(root, sources, shared)

    def check_file(self, src: SourceFile) -> Iterable[Finding]:
        decl = GUARDED_BY.get(src.rel)
        if not decl or self._model is None:
            return ()
        findings: List[Finding] = []
        observed = self._observed.setdefault(src.rel, set())
        seen: Set[Tuple[int, str]] = set()
        for fn in self._model.functions_in(src.rel):
            exempt = (fn.name == "__init__" or fn.name.endswith("_locked"))
            for _, node, held in fn.events:
                for sub in locks_mod._walk_no_defs(node):
                    site = _site_of(sub)
                    if site is None or site not in decl:
                        continue
                    observed.add(site)
                    if exempt or decl[site] in held:
                        continue
                    key = (sub.lineno, site)
                    if key in seen:
                        continue
                    seen.add(key)
                    if self.suppressed(src, sub.lineno, findings):
                        continue
                    findings.append(Finding(
                        src.rel, sub.lineno, self.id,
                        f"'{fn.qual}' touches {site} outside 'with "
                        f"<{decl[site]} lock>:' — GUARDED_BY declares "
                        f"{site} guarded by '{decl[site]}' (take the lock, "
                        "move the access into a *_locked helper, or "
                        "annotate the deliberate dirty read)"))
        return findings

    def finalize(self, root: str) -> Iterable[Finding]:
        model, self._model = self._model, None
        observed, self._observed = self._observed, {}
        findings: List[Finding] = []
        for rel, decl in GUARDED_BY.items():
            path = os.path.join(root, rel.replace("/", os.sep))
            if not os.path.exists(path):
                continue
            for site in sorted(set(decl) - observed.get(rel, set())):
                findings.append(Finding(
                    rel, 0, self.id,
                    f"GUARDED_BY declares {site} but the module never "
                    "touches it — prune the stale declaration"))
            if model is not None:
                module_locks = {s.name for s in model.sites_in(rel)}
                for site, lock in sorted(decl.items()):
                    if lock not in module_locks:
                        findings.append(Finding(
                            rel, 0, self.id,
                            f"GUARDED_BY guards {site} with lock '{lock}' "
                            "but no such lock is created in this module — "
                            "fix the declaration"))
        return findings
