"""fault-barrier: broad exception catches exist only at declared barriers.

Migrated from the standalone ``tools/lint_fault_barrier.py`` (PR 1), which
remains as a thin shim over this module so its CLI contract and
``tests/test_fault_barrier_lint.py`` keep holding. The invariant is unchanged:

1. every ``except Exception`` / ``except BaseException`` / bare ``except:``
   line carries a ``# fault-barrier: <reason>`` comment;
2. per-file broad-catch counts match the ``ALLOWED`` declaration — adding a
   barrier is a deliberate act that edits this file, not a drive-by.

This rule manages its own annotation grammar (the legacy line-level marker,
which is also valid ``# <rule-id>: <reason>`` vftlint grammar) and count
reconciliation; prefer raising the classified taxonomy from
``video_features_tpu/reliability/errors.py`` over adding a barrier.
"""

from __future__ import annotations

import os
import re
from typing import Dict, Iterable, List, Tuple

from ..core import Finding, Rule, register

# Declared barriers: package-relative posix path -> expected broad-catch count.
ALLOWED: Dict[str, int] = {
    "video_features_tpu/cache/store.py": 2,        # read + publish: a cache entry of ANY state must degrade to a miss / pass-through, never crash the video it would have saved
    "video_features_tpu/extractors/base.py": 7,    # per-video fault barrier (per-video + packed loops) + packed finalize + corpus-flush arms + async-write reap arm + unwind-path write accounting + segment-planner probe (falls back to sequential open)
    "video_features_tpu/extractors/flow.py": 3,    # async-copy + imshow probes + precompile warmup
    "video_features_tpu/io/output.py": 1,          # writer thread: error stored on the WriteHandle
    "video_features_tpu/parallel/packer.py": 4,    # stale-flush + corpus-flush, dispatch + scatter arms each: every bucket's victims, not the finisher or a healthy co-resident bucket/model, own the failure
    "video_features_tpu/parallel/pipeline.py": 3,  # distributed-client probe + worker re-raise + segment planner (falls back to sequential scheduling)
    "video_features_tpu/reliability/retry.py": 2,  # classified re-raise + attempts attr
    "video_features_tpu/reliability/watchdog.py": 1,  # hands the exception to the waiter
    "video_features_tpu/run.py": 1,                # best-effort JAX_PLATFORMS shim
    "video_features_tpu/serve/daemon.py": 7,       # per-video isolation point (serving loop) + lazy model-construction arm + cache-hit write arm + best-effort rejection/result records (the daemon must outlive a full notify disk) + profile start/stop arms (an on-demand jax.profiler session failing must report over the socket, not kill the API thread)
    "video_features_tpu/serve/ingest.py": 1,       # one bad socket client must not kill the API thread
    "video_features_tpu/serve/wal.py": 1,          # writer-thread wrapper: a dead writer would hang every submitter blocked on its ack event — degrade loudly and keep acking
}

MARKER = "fault-barrier:"
BROAD = re.compile(r"^\s*except\s*(\(\s*)?(Base)?Exception\b|^\s*except\s*:")


def scan(repo_root: str) -> Tuple[List[str], Dict[str, int]]:
    """(findings, per-file broad-catch counts) for the package tree.

    Kept line-based (not AST) deliberately: the marker must sit on the
    ``except`` line itself, and the scan must also work on files that fail
    to parse mid-edit. Message strings are the PR-1 originals — the shim's
    output is part of its contract.
    """
    findings: List[str] = []
    counts: Dict[str, int] = {}
    pkg = os.path.join(repo_root, "video_features_tpu")
    for dirpath, _dirnames, filenames in os.walk(pkg):
        for name in sorted(filenames):
            if not name.endswith(".py"):
                continue
            path = os.path.join(dirpath, name)
            rel = os.path.relpath(path, repo_root).replace(os.sep, "/")
            with open(path, encoding="utf-8") as f:
                for lineno, line in enumerate(f, start=1):
                    if not BROAD.match(line):
                        continue
                    counts[rel] = counts.get(rel, 0) + 1
                    if MARKER not in line:
                        findings.append(
                            f"{rel}:{lineno}: broad except without a "
                            f"'{MARKER}' justification comment — raise a "
                            "classified reliability error instead, or declare "
                            "the barrier"
                        )
    for rel, n in sorted(counts.items()):
        want = ALLOWED.get(rel)
        if want is None:
            findings.append(
                f"{rel}: {n} broad except(s) in a file with no declared "
                "barriers — new broad catches must be added to "
                "tools/lint_fault_barrier.py ALLOWED deliberately"
            )
        elif n != want:
            findings.append(
                f"{rel}: expected {want} declared barrier(s), found {n} — "
                "update tools/lint_fault_barrier.py ALLOWED if intentional"
            )
    for rel, want in sorted(ALLOWED.items()):
        if rel not in counts and os.path.exists(os.path.join(repo_root, rel)):
            findings.append(
                f"{rel}: allowlist expects {want} barrier(s) but none found — "
                "prune the stale ALLOWED entry"
            )
    return findings, counts


@register
class FaultBarrierRule(Rule):
    id = "fault-barrier"
    title = "broad excepts only at declared, annotated fault barriers"
    roots = ("video_features_tpu",)

    def annotation_live(self, src, line: int) -> bool:
        # this rule's grammar is line-level (the marker must sit on the
        # broad-except line itself, or the line above it vftlint-style), so
        # "live" means: the annotated line is still a broad except
        lines = src.text.splitlines()
        for ln in (line, line + 1):
            if 1 <= ln <= len(lines) and BROAD.match(lines[ln - 1]):
                return True
        return False

    # scan() is whole-tree; run it once from finalize instead of per file
    def finalize(self, root: str) -> Iterable[Finding]:
        findings: List[Finding] = []
        for text in scan(root)[0]:
            loc, _, message = text.partition(": ")
            path, _, lineno = loc.partition(":")
            findings.append(Finding(
                path, int(lineno) if lineno else 0, self.id, message))
        return findings
