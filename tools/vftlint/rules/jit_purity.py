"""jit-purity: no host side effects inside XLA-traced function bodies.

A ``print``/``time.time``/``datetime.now``/stdlib-``random``/file-I/O call in
a jitted function runs ONCE, at trace time, then silently never again — the
classic "my debug print only fired for the first batch" bug — and anything it
computes is burned into the compiled program as a constant. Host effects
belong outside the traced region (or behind ``jax.debug.print`` /
``io_callback``, which this rule deliberately does not match).

Suppress a deliberate trace-time effect with ``# jit-purity: <reason>``.
"""

from __future__ import annotations

import ast
from typing import Iterable, List

from ..core import Finding, Rule, SourceFile, register
from ..tracing import dotted_name, walk_body

# builtins that are host effects wherever they appear in a traced body
_BANNED_BUILTINS = {"print", "open", "input", "breakpoint"}

# dotted-call suffixes that are host effects; matched against the full
# callee chain so `jax.random.normal` (fine) never collides with stdlib
# `random.normal` (banned root below)
_BANNED_CALLS = {
    "time.time", "time.monotonic", "time.perf_counter", "time.process_time",
    "time.sleep",
    "datetime.now", "datetime.utcnow", "datetime.today",
    "datetime.datetime.now", "datetime.datetime.utcnow",
    "np.save", "np.load", "np.savez", "numpy.save", "numpy.load",
    "os.remove", "os.replace", "os.rename", "os.unlink", "os.makedirs",
    "os.mkdir", "os.open", "os.system",
}

# any call rooted at the stdlib `random` module (random.random, random.seed…)
_BANNED_ROOTS = {"random"}


@register
class JitPurityRule(Rule):
    id = "jit-purity"
    title = "no host side effects inside jitted/shard_mapped functions"
    roots = ("video_features_tpu",)

    def check_file(self, src: SourceFile) -> Iterable[Finding]:
        findings: List[Finding] = []
        for fn in src.traced():  # memoized: shared with host-sync
            for node in walk_body(fn):
                if not isinstance(node, ast.Call):
                    continue
                name = dotted_name(node.func)
                if name is None:
                    continue
                bad = None
                if name in _BANNED_BUILTINS:
                    bad = f"'{name}()'"
                elif name in _BANNED_CALLS:
                    bad = f"'{name}()'"
                elif name.split(".", 1)[0] in _BANNED_ROOTS and "." in name:
                    bad = f"stdlib '{name}()'"
                if bad is None:
                    continue
                if self.suppressed(src, node.lineno, findings):
                    continue
                findings.append(Finding(
                    src.rel, node.lineno, self.id,
                    f"{bad} inside traced function '{fn.name}' runs at "
                    "trace time only — move it out of the jitted region "
                    "(or use jax.debug / io_callback)"))
        return findings
