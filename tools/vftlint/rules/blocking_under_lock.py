"""blocking-under-lock: no blocking work while holding any lock.

A lock scope is a convoy: every thread that wants the lock waits for
whatever the holder is doing. Disk writes, blocking queue puts, socket I/O,
``time.sleep``, subprocess calls, and device syncs (``Extractor._wait`` /
``block_until_ready``) all turn a microsecond critical section into a
latency cliff — the PR 10 review's "registry reads copy under the lock and
format outside it" finding, generalized. The rule flags:

- a direct blocking sink (:func:`..locks.classify_sink`) lexically inside a
  ``with <lock>:`` block — including ``print`` (stdout to a pipe blocks)
  and ``open`` (the file-I/O chokepoint);
- a call under a held lock whose callee MAY (transitively, through the
  lock model's name-resolved call graph) reach a blocking sink — the
  ``with self._lock: self._finish(...)`` three-frames-to-a-file-write
  shape that hand review kept catching.

Non-blocking forms are exempt by construction: ``put_nowait``/``get_nowait``
and ``block=False`` queue ops (the journal's producer path), plus anything
the model cannot resolve (indirection under-approximates; keep lock scopes
direct). Suppress a deliberate block with ``# blocking-under-lock:
<reason>`` on the offending line — and expect the review to ask why the
work cannot move outside the lock instead.
"""

from __future__ import annotations

from typing import Dict, Iterable, List, Optional

from ..core import Finding, Rule, SourceFile, register
from .. import locks as locks_mod


@register
class BlockingUnderLockRule(Rule):
    id = "blocking-under-lock"
    title = "no blocking sinks (I/O, sleep, queue waits) while a lock is held"
    roots = ("video_features_tpu",)

    def __init__(self) -> None:
        self._model: Optional[locks_mod.LockModel] = None

    def prepare(self, root, sources, shared) -> None:
        self._model = locks_mod.shared_model(root, sources, shared)

    def check_file(self, src: SourceFile) -> Iterable[Finding]:
        model = self._model
        if model is None:
            return ()
        findings: List[Finding] = []
        for fn in model.functions_in(src.rel):
            for desc, line, held in fn.sink_events:
                if not held:
                    continue
                if self.suppressed(src, line, findings):
                    continue
                findings.append(Finding(
                    src.rel, line, self.id,
                    f"blocking {desc} while '{fn.qual}' holds "
                    f"{self._locks(held)} — move the blocking work outside "
                    "the lock (snapshot under the lock, act after release)"))
            for call, line, held in fn.call_events:
                sinks = model.call_effect_sinks(call, fn)
                if not sinks:
                    continue
                desc, chain = min(sinks.items(), key=lambda kv: len(kv[1]))
                if self.suppressed(src, line, findings):
                    continue
                findings.append(Finding(
                    src.rel, line, self.id,
                    f"call under {self._locks(held)} reaches blocking "
                    f"{desc} via {' -> '.join(chain)} — move the call "
                    "outside the lock (snapshot under the lock, act after "
                    "release)"))
        return findings

    def finalize(self, root: str) -> Iterable[Finding]:
        self._model = None
        return ()

    @staticmethod
    def _locks(held) -> str:
        names = ", ".join(f"'{h}'" for h in held)
        return f"lock {names}" if len(held) == 1 else f"locks {names}"
