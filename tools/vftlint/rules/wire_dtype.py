"""wire-dtype: the frame wire ships uint8; floats are made on device.

PR 14 moved normalization/resize into the jitted step precisely so the
host→device wire carries raw ``uint8`` pixels — a float32 wire is 4× the
PCIe/ICI bytes and erases the win. The one sanctioned exception is the
``--float32_wire`` escape (``flow.py``'s ``self._wire = np.float32 if
cfg.float32_wire else np.uint8``), kept for parity runs against the
reference checkpoints.

This rule taints values produced by ``.astype(<float dtype>)`` (and values
derived from them — slicing, arithmetic, ``np.ascontiguousarray``,
``HostStagingRing.stage`` assembly) and flags any tainted value reaching a
*staging sink*: ``self._put`` / ``_put_replicated`` / ``runner.put`` /
``put_replicated`` / ``jax.device_put`` / ``_stage_rows`` /
``prefetch_to_device``, including calls through a local alias
(``put = self._put if timed else self.runner.put``). Casts *inside* traced
step bodies are invisible here by construction — they happen on device,
which is the whole point.

The escape is structural, not a suppression: a cast or sink lexically
guarded by a ``float32_wire`` conditional (the ``if`` test or ``IfExp``
mentions the flag) is exempt. Audio is exempt wholesale — VGGish ships
float PCM by design (``extractors/vggish.py``; there is no uint8 wire for
waveforms).

Suppress a deliberate float staging with ``# wire-dtype: <reason>``.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterable, List, Set

from ..core import Finding, Rule, SourceFile, register
from ..dataflow import LineOrderScanner, walk_no_defs
from ..tracing import dotted_name

FLOAT_DTYPES = {"float", "float16", "float32", "float64", "bfloat16",
                "half", "single", "double"}

# call last-names that stage a host buffer onto the device
_SINK_NAMES = {"_put", "_put_replicated", "_stage_rows",
               "device_put", "prefetch_to_device"}
# attr names that are sinks when read through a runner-/staging-ish receiver
_RECV_SINKS = {"put": ("runner",), "put_replicated": ("runner",),
               "stage": ("staging", "ring"), "commit": ("staging", "ring")}

_ESCAPE_TOKEN = "float32_wire"

# python files exempt wholesale: float PCM audio wire by design
_EXEMPT_FILES = {"video_features_tpu/extractors/vggish.py"}


def _mentions_escape(node: ast.AST) -> bool:
    for sub in ast.walk(node):
        if isinstance(sub, ast.Attribute) and _ESCAPE_TOKEN in sub.attr:
            return True
        if isinstance(sub, ast.Name) and _ESCAPE_TOKEN in sub.id:
            return True
    return False


def _float_dtype_literal(node: ast.AST) -> bool:
    """Is ``node`` a literal float dtype (``np.float32``, ``jnp.bfloat16``,
    ``"float32"``, bare ``float``)?"""
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value in FLOAT_DTYPES or node.value.startswith("float")
    name = dotted_name(node) or ""
    return name.rsplit(".", 1)[-1] in FLOAT_DTYPES


def _is_sink_attr(node: ast.AST) -> bool:
    """An attribute READ that denotes a staging sink (for alias tracking)."""
    if not isinstance(node, ast.Attribute):
        return False
    if node.attr in _SINK_NAMES:
        return True
    tokens = _RECV_SINKS.get(node.attr)
    if tokens is None:
        return False
    recv = (dotted_name(node.value) or "").lower()
    return any(t in recv for t in tokens)


class _Scanner(LineOrderScanner):
    """State: ``tainted`` names (hold host float-cast frame data),
    ``float_names`` (names bound to an unconditional float dtype literal),
    ``sink_aliases`` (names bound to a staging-sink bound method)."""

    def __init__(self, rule: "WireDtypeRule", src: SourceFile,
                 findings: List[Finding]):
        self.rule = rule
        self.src = src
        self.findings = findings
        self.tainted: Set[str] = set()
        self.float_names: Set[str] = set()
        self.sink_aliases: Set[str] = set()
        self._escape_depth = 0

    # -- state protocol -----------------------------------------------------

    def snapshot(self):
        return (set(self.tainted), set(self.float_names),
                set(self.sink_aliases))

    def restore(self, token) -> None:
        self.tainted = set(token[0])
        self.float_names = set(token[1])
        self.sink_aliases = set(token[2])

    def merged(self, tokens):
        out = [set(), set(), set()]
        for t in tokens:
            for i in range(3):
                out[i] |= t[i]
        return tuple(out)

    # -- taint --------------------------------------------------------------

    def _casts_float(self, dtype_arg: ast.AST) -> bool:
        if _float_dtype_literal(dtype_arg):
            return True
        if isinstance(dtype_arg, ast.Name):
            return dtype_arg.id in self.float_names
        return False

    def is_tainted(self, node: ast.AST) -> bool:
        if isinstance(node, ast.Name):
            return node.id in self.tainted
        if isinstance(node, ast.Subscript):
            return self.is_tainted(node.value)
        if isinstance(node, ast.Starred):
            return self.is_tainted(node.value)
        if isinstance(node, (ast.Tuple, ast.List)):
            return any(self.is_tainted(e) for e in node.elts)
        if isinstance(node, ast.BinOp):
            return self.is_tainted(node.left) or self.is_tainted(node.right)
        if isinstance(node, ast.UnaryOp):
            return self.is_tainted(node.operand)
        if isinstance(node, ast.IfExp):
            return self.is_tainted(node.body) or self.is_tainted(node.orelse)
        if isinstance(node, ast.Call):
            if (isinstance(node.func, ast.Attribute)
                    and node.func.attr == "astype" and node.args
                    and self._casts_float(node.args[0])):
                return True
            # a call on/of tainted data stays tainted (ascontiguousarray,
            # staging assembly, reshape…)
            if any(self.is_tainted(a) for a in node.args):
                return True
            if (isinstance(node.func, ast.Attribute)
                    and self.is_tainted(node.func.value)):
                return True
        return False

    # -- sinks --------------------------------------------------------------

    def _check_sinks(self, root: ast.AST) -> None:
        if self._escape_depth:
            return
        for node in walk_no_defs(root):
            if not isinstance(node, ast.Call):
                continue
            is_sink = _is_sink_attr(node.func) or (
                isinstance(node.func, ast.Name)
                and (node.func.id in self.sink_aliases
                     or node.func.id in _SINK_NAMES))
            if not is_sink:
                continue
            if not any(self.is_tainted(a) for a in node.args):
                continue
            label = dotted_name(node.func) or getattr(
                node.func, "attr", "put")
            if self.rule.suppressed(self.src, node.lineno, self.findings):
                continue
            self.findings.append(Finding(
                self.src.rel, node.lineno, self.rule.id,
                f"float-cast value reaches staging sink {label}() — the "
                "frame wire ships uint8 (cast on device inside the jitted "
                "step); deliberate float staging belongs behind the "
                "--float32_wire escape"))

    # -- walk hooks ---------------------------------------------------------

    def visit_expr(self, expr: ast.AST) -> None:
        self._check_sinks(expr)

    def scan_branch(self, body, stmt: ast.If, index: int) -> None:
        # `if cfg.float32_wire:` — the true arm is the declared escape
        gated = index == 0 and _mentions_escape(stmt.test)
        if gated:
            self._escape_depth += 1
        self.scan_block(body)
        if gated:
            self._escape_depth -= 1

    def visit_simple(self, stmt: ast.stmt) -> None:
        self._check_sinks(stmt)
        if isinstance(stmt, ast.Assign):
            self._assign(stmt.targets, stmt.value)
        elif isinstance(stmt, ast.AnnAssign) and stmt.value is not None:
            self._assign([stmt.target], stmt.value)
        elif isinstance(stmt, ast.AugAssign):
            if self.is_tainted(stmt.value):
                self._mark(stmt.target, True)

    def _assign(self, targets, value: ast.AST) -> None:
        # `wire = np.float32 if cfg.float32_wire else np.uint8` is the
        # declared escape shape: the name is NOT an unconditional float
        escaped = isinstance(value, ast.IfExp) and _mentions_escape(
            value.test)
        tainted = not escaped and not self._escape_depth and self.is_tainted(
            value)
        floaty = (not escaped and not self._escape_depth
                  and _float_dtype_literal(value))
        sink_alias = _is_sink_attr(value) or (
            isinstance(value, ast.IfExp)
            and (_is_sink_attr(value.body) or _is_sink_attr(value.orelse)))
        for target in targets:
            self._mark(target, tainted)
            if isinstance(target, ast.Name):
                if floaty:
                    self.float_names.add(target.id)
                else:
                    self.float_names.discard(target.id)
                if sink_alias:
                    self.sink_aliases.add(target.id)
                else:
                    self.sink_aliases.discard(target.id)

    def _mark(self, target: ast.AST, tainted: bool) -> None:
        if isinstance(target, ast.Name):
            if tainted:
                self.tainted.add(target.id)
            else:
                self.tainted.discard(target.id)
        elif isinstance(target, (ast.Tuple, ast.List)):
            for elt in target.elts:
                self._mark(elt, tainted)
        elif isinstance(target, ast.Starred):
            self._mark(target.value, tainted)


@register
class WireDtypeRule(Rule):
    id = "wire-dtype"
    title = "frame staging ships uint8; floats behind --float32_wire only"
    roots = ("video_features_tpu/extractors", "video_features_tpu/parallel")

    def wants(self, rel: str) -> bool:
        return rel.endswith(".py") and rel not in _EXEMPT_FILES

    def check_file(self, src: SourceFile) -> Iterable[Finding]:
        findings: List[Finding] = []
        defs = [n for n in ast.walk(src.tree)
                if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))]
        nested = {sub for fn in defs for sub in ast.walk(fn)
                  if sub is not fn
                  and isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef))}
        for node in defs:
            if node in nested:
                continue
            _Scanner(self, src, findings).scan_block(node.body)
        return sorted(set(findings),
                      key=lambda f: (f.path, f.line, f.message))
