"""recompile-hygiene: jitted callables are built once, not per call.

PR 13 fixed a real leak: ``pack_spec()`` rebuilt its paged program on every
call, so every video paid a fresh XLA compile (seconds on TPU) and the
compile cache grew without bound. The fix — ``Extractor._paged_fields``
memoizing ``runner.jit_paged(paged_program(forward))`` per (forward,
page_rows, depth) — is the contract this rule freezes mechanically.

A *jit construction* is a call whose name ends in ``jit``/``jit_paged``/
``sharded_apply``/``paged_program``/``pjit`` (``jax.jit``, ``runner.jit``,
bare ``sharded_apply`` — the wiring in ``parallel/mesh.py``). One is a
finding when it happens:

- lexically inside a ``for``/``while`` loop, or
- in a function reachable from any ``pack_spec()``/``extract()`` method via
  the name-based call graph (:mod:`tools.vftlint.locks` — the same
  resolution the lock rules use), i.e. it runs per video / per batch,

unless the constructed callable flows into a **declared memo table**
(:data:`MEMO_TABLES`): the construction is dominated by a miss on
``self._paged_programs[...]`` / ``self._frames_steps[...]`` (directly or
through a local alias like ``cache = self.__dict__.setdefault(...)``), so
it runs once per key.

Construction sites that are once-per-object by construction are exempt:
``__init__``, ``functools.cached_property``/``property``-decorated getters,
and the wiring functions themselves (``sharded_apply``/``MeshRunner.jit``/
``jit_paged``/``paged_program`` exist to build jitted callables). Those
exempt functions are also barriers for reachability — a builder invoked
only from a ``cached_property`` getter runs once, not per call.

Suppress a deliberate per-call construction with
``# recompile-hygiene: <reason>``.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterable, List, Optional, Set, Tuple

from ..core import Finding, Rule, SourceFile, register
from ..dataflow import walk_no_defs
from ..locks import FnSummary, shared_model
from ..tracing import dotted_name

# call last-names that construct a jitted callable
CONSTRUCTORS = {"jit", "pjit", "jit_paged", "sharded_apply", "paged_program"}

# per-call entry points: these run per video / per packed batch
ENTRYPOINTS = {"pack_spec", "extract"}

# declared memo tables: a construction stored into one is once-per-key
MEMO_TABLES = {"_paged_programs", "_frames_steps"}

# once-per-object decorators (construction inside these is hoisted by design)
_ONCE_DECORATORS = {"cached_property", "property", "lru_cache", "cache"}


def _is_exempt(fn: FnSummary) -> bool:
    if fn.name == "__init__" or fn.name in CONSTRUCTORS:
        return True
    for dec in getattr(fn.node, "decorator_list", ()):
        target = dec.func if isinstance(dec, ast.Call) else dec
        name = dotted_name(target) or ""
        if name.rsplit(".", 1)[-1] in _ONCE_DECORATORS:
            return True
    return False


def _construct_call(node: ast.AST) -> Optional[str]:
    if not isinstance(node, ast.Call):
        return None
    name = dotted_name(node.func) or ""
    if name.rsplit(".", 1)[-1] in CONSTRUCTORS:
        return name
    return None


class _Site:
    __slots__ = ("call", "name", "target", "in_loop")

    def __init__(self, call: ast.Call, name: str,
                 target: Optional[str], in_loop: bool):
        self.call = call
        self.name = name          # dotted constructor name, for the message
        self.target = target      # single Name the result is assigned to
        self.in_loop = in_loop


def _scan_fn(fn: FnSummary) -> Tuple[List[_Site], Set[str]]:
    """(construction sites, names stored into a declared memo table) for one
    function body — nested defs excluded (they are their own summaries)."""
    aliases: Set[str] = set()
    for sub in walk_no_defs(ast.Module(body=fn.node.body, type_ignores=[])):
        if not isinstance(sub, ast.Assign):
            continue
        mentions_memo = any(
            (isinstance(n, ast.Attribute) and n.attr in MEMO_TABLES)
            or (isinstance(n, ast.Constant) and n.value in MEMO_TABLES)
            for n in ast.walk(sub.value))
        if mentions_memo:
            for t in sub.targets:
                if isinstance(t, ast.Name):
                    aliases.add(t.id)

    sites: List[_Site] = []
    stored: Set[str] = set()

    def exprs(st: ast.stmt):
        for child in ast.iter_child_nodes(st):
            if not isinstance(child, ast.stmt):
                yield child

    def visit(stmts, in_loop: bool) -> None:
        for st in stmts:
            if isinstance(st, (ast.FunctionDef, ast.AsyncFunctionDef,
                               ast.ClassDef)):
                continue
            target: Optional[str] = None
            if (isinstance(st, ast.Assign) and len(st.targets) == 1
                    and isinstance(st.targets[0], ast.Name)):
                target = st.targets[0].id
            if isinstance(st, ast.Assign):
                for t in st.targets:
                    if (isinstance(t, ast.Subscript)
                            and isinstance(st.value, ast.Name)):
                        table = t.value
                        if ((isinstance(table, ast.Attribute)
                             and table.attr in MEMO_TABLES)
                                or (isinstance(table, ast.Name)
                                    and table.id in aliases)):
                            stored.add(st.value.id)
            for expr in exprs(st):
                for sub in walk_no_defs(expr):
                    name = _construct_call(sub)
                    if name is not None:
                        sites.append(_Site(sub, name, target, in_loop))
            loop = in_loop or isinstance(st, (ast.For, ast.AsyncFor,
                                              ast.While))
            for field in ("body", "orelse", "finalbody"):
                visit(getattr(st, field, []) or [], loop)
            for handler in getattr(st, "handlers", []) or []:
                visit(handler.body, loop)

    visit(fn.node.body, False)
    return sites, stored


@register
class RecompileHygieneRule(Rule):
    id = "recompile-hygiene"
    title = "jit construction memoized, not per pack_spec()/extract() call"
    roots = ("video_features_tpu",)

    def prepare(self, root: str, sources, shared) -> None:
        self._model = shared_model(root, sources, shared)
        # BFS from every pack_spec/extract over the name-based call graph;
        # exempt functions are barriers (they run once per object)
        self._via: Dict[int, Tuple[str, ...]] = {}
        queue: List[FnSummary] = []
        for fn in self._model.functions:
            if fn.name in ENTRYPOINTS:
                self._via[id(fn)] = (fn.qual,)
                queue.append(fn)
        while queue:
            fn = queue.pop(0)
            if _is_exempt(fn):
                continue
            chain = self._via[id(fn)]
            if len(chain) >= 5:
                continue
            for callee in self._model.callees(fn):
                if id(callee) not in self._via:
                    self._via[id(callee)] = chain + (callee.qual,)
                    queue.append(callee)

    def check_file(self, src: SourceFile) -> Iterable[Finding]:
        findings: List[Finding] = []
        for fn in self._model.functions_in(src.rel):
            sites, stored = _scan_fn(fn)
            if not sites:
                continue
            exempt = _is_exempt(fn)
            chain = self._via.get(id(fn))
            for site in sites:
                memoized = site.target is not None and site.target in stored
                if memoized:
                    continue
                if site.in_loop:
                    if self.suppressed(src, site.call.lineno, findings):
                        continue
                    findings.append(Finding(
                        src.rel, site.call.lineno, self.id,
                        f"{site.name}() constructed inside a loop — every "
                        "iteration pays a fresh XLA compile; hoist it or "
                        "memoize into a declared table "
                        f"({', '.join(sorted(MEMO_TABLES))}, the "
                        "_paged_fields pattern)"))
                    continue
                if exempt or chain is None:
                    continue
                if self.suppressed(src, site.call.lineno, findings):
                    continue
                findings.append(Finding(
                    src.rel, site.call.lineno, self.id,
                    f"{site.name}() constructed per call: '{fn.qual}' is "
                    f"reachable from the per-video path via "
                    f"{' → '.join(chain)} — memoize into a declared table "
                    f"({', '.join(sorted(MEMO_TABLES))}) or hoist to "
                    "__init__/cached_property"))
        return sorted(set(findings),
                      key=lambda f: (f.path, f.line, f.message))
