"""thread-shared-state: cross-thread writes follow a declared discipline.

PR 1/2 introduced five threading seams (async output writer, per-video
watchdog, decode prefetch pool, flow geometry precompile, fault-injection
lock) whose safety arguments lived only in comments. This rule turns them
into checked declarations, the race-detector analogue of the fault-barrier
allowlist:

1. a module may spawn ``threading.Thread`` only if it is listed in
   ``THREAD_MODULES`` below — adding a threading seam is a deliberate act
   that edits this file, not a drive-by;
2. every store to shared state inside a thread-entry function (the
   ``target=`` of a ``Thread(...)`` call, nested defs included) — an
   attribute or subscript whose base is not a thread-local name — must
   carry a ``# thread-shared-state: <reason>`` annotation naming the
   lock/Event discipline that publishes it, AND appear in the module's
   ``SHARED_WRITES`` declaration.

The declared sites and their disciplines:

- ``io/output.py`` ``handle._error``: written by the writer thread strictly
  before ``handle._done.set()``; readers block on the Event (happens-before).
- ``parallel/pipeline.py`` ``slot['meta']`` / ``slot['err']``: written by the
  decode worker strictly before ``slot['ready'].set()`` (err also before the
  ``_DONE`` sentinel enqueue); consumers wait on the Event / sentinel.
- ``parallel/pipeline.py`` ``slot['bytes']``: the byte-cap accounting for the
  decode buffer — incremented by the worker after each enqueue, decremented
  by the consumer's drain after each dequeue, both under ``slot['lock']``.
- ``obs/journal.py`` ``self._written`` / ``self._write_errors``: advanced
  only by the single telemetry-writer thread; ``stats()`` readers take a
  GIL-atomic load of a monotone int (an off-by-one-moment read is fine for
  a counter that only reports).

``reliability/watchdog.py`` and ``extractors/flow.py`` spawn threads whose
targets publish through list-append / Event-set / queue operations only —
no shared stores to declare. ``parallel/packer.py`` (the corpus clip packer)
spawns NO threads by design: its one consumer thread owns all packing state,
and its cross-thread traffic rides the pipeline/output seams above. The
feature cache (``cache/``) likewise spawns no threads and needs no
declarations: the store and the in-flight coalescer are owned by the run
loop / daemon thread (cache publishes happen inline in ``_submit_outputs``,
BEFORE the async writer takes the job), and cross-process cache sharing
rides atomic renames, not shared memory.
"""

from __future__ import annotations

import ast
import os
from typing import Dict, Iterable, List, Optional, Set, Tuple

from ..core import Finding, Rule, SourceFile, register
from ..tracing import dotted_name

# modules allowed to spawn threads (package-relative posix paths)
THREAD_MODULES: Dict[str, str] = {
    "video_features_tpu/io/output.py": "async output writer (single-writer queue)",
    "video_features_tpu/parallel/pipeline.py": "decode prefetch pool",
    "video_features_tpu/reliability/watchdog.py": "per-video watchdog worker",
    "video_features_tpu/extractors/flow.py": "geometry precompile warmup",
    # ThreadPoolExecutor (not a bare Thread(...), so the spawn scan does not
    # see it) — declared here anyway per this rule's contract: workers return
    # values only, assembly happens on the calling thread, no shared stores
    "video_features_tpu/io/video.py": "corpus geometry probe pool (prepare)",
    # spool-watcher + socket-API ingest threads: both publish exclusively
    # through ExtractionService's RLock-guarded methods and the RequestQueue
    # lock — the thread entries themselves store nothing shared
    "video_features_tpu/serve/ingest.py": "spool watcher + socket API ingest",
    # telemetry journal writer: one bounded single-writer thread appending
    # JSONL (the AsyncOutputWriter discipline applied to telemetry);
    # producers only queue-put, the writer only advances its own counters
    "video_features_tpu/obs/journal.py": "telemetry journal writer",
    # WAL writer: one single-writer thread owns the admission log file;
    # producers queue-put and block on per-record ack Events, shared flags
    # (_unresolved/_degraded) live under the 'wal' lock (GUARDED_BY)
    "video_features_tpu/serve/wal.py":
        "write-ahead admission log writer (single-writer queue; ack via "
        "per-record Events)",
    # hung-step watchdog monitor: communicates with the daemon thread via
    # threading.Events only (_stalled/_watchdog_stop) — no shared stores
    "video_features_tpu/serve/daemon.py":
        "hung-step watchdog monitor (Events only)",
}

# declared cross-thread stores: module -> {canonical site: discipline}
SHARED_WRITES: Dict[str, Dict[str, str]] = {
    "video_features_tpu/io/output.py": {
        "handle._error": "set before _done Event; wait() reads after it",
    },
    "video_features_tpu/parallel/pipeline.py": {
        "slot['meta']": "set before the ready Event",
        "slot['err']": "set before the ready Event / _DONE sentinel",
        "slot['bytes']": "guarded by slot['lock'] (worker increments after "
                         "enqueue; the consumer drain decrements after "
                         "dequeue under the same lock)",
        # segmented decode: the only NEW cross-thread store a decode worker
        # makes is its completion counter; the permit-accounting counters
        # (_busy/_pending_baselines/_videos_segmented) are stored from
        # schedule()-caller helpers and policed by the GUARDED_BY table
        "self._segments_decoded": "guarded by the 'resize' lock "
                                  "(segment_stats reads under it)",
    },
    "video_features_tpu/obs/journal.py": {
        "self._written": "written only by the single writer thread; stats "
                         "readers take a GIL-atomic monotone int load",
        "self._write_errors": "written only by the single writer thread; "
                              "stats readers take a GIL-atomic monotone "
                              "int load",
    },
}


def _canonical(target: ast.AST) -> Optional[str]:
    """'base.attr' / "base['key']" for attribute/subscript store targets whose
    base is a plain name; None for stores to local names (thread-private)."""
    if isinstance(target, ast.Attribute):
        base = dotted_name(target.value)
        return f"{base}.{target.attr}" if base else None
    if isinstance(target, ast.Subscript):
        base = dotted_name(target.value)
        if base is None:
            return None
        key = target.slice
        if isinstance(key, ast.Constant) and isinstance(key.value, str):
            return f"{base}[{key.value!r}]"
        return f"{base}[...]"
    return None


def _thread_targets(tree: ast.AST) -> Set[ast.AST]:
    """FunctionDef nodes used as ``target=`` of a ``Thread(...)`` call."""
    defs_by_name: Dict[str, List] = {}
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            defs_by_name.setdefault(node.name, []).append(node)

    targets: Set[ast.AST] = set()
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        name = (dotted_name(node.func) or "").rsplit(".", 1)[-1]
        if name != "Thread":
            continue
        for kw in node.keywords:
            if kw.arg != "target":
                continue
            if isinstance(kw.value, ast.Name):
                targets.update(defs_by_name.get(kw.value.id, ()))
            elif isinstance(kw.value, ast.Attribute):
                # self._drain → resolve the method by name
                targets.update(defs_by_name.get(kw.value.attr, ()))
    return targets


@register
class ThreadSharedStateRule(Rule):
    id = "thread-shared-state"
    title = "cross-thread stores follow a declared lock/Event discipline"
    roots = ("video_features_tpu",)

    def __init__(self) -> None:
        self._observed: Dict[str, Set[str]] = {}

    def check_file(self, src: SourceFile) -> Iterable[Finding]:
        findings: List[Finding] = []
        spawns = [n for n in ast.walk(src.tree)
                  if isinstance(n, ast.Call)
                  and (dotted_name(n.func) or "").rsplit(".", 1)[-1] == "Thread"]
        if not spawns:
            return findings
        if src.rel not in THREAD_MODULES:
            for call in spawns:
                findings.append(Finding(
                    src.rel, call.lineno, self.id,
                    "threading.Thread in a module with no declared threading "
                    "seam — declare it in THREAD_MODULES "
                    "(tools/vftlint/rules/thread_shared_state.py) and "
                    "document its shared-state discipline"))
            return findings

        declared = SHARED_WRITES.get(src.rel, {})
        observed = self._observed.setdefault(src.rel, set())
        for fn in _thread_targets(src.tree):
            for site, node in self._shared_stores(fn):
                observed.add(site)
                reason = src.annotation(self.id, node.lineno)
                if reason is None:
                    findings.append(Finding(
                        src.rel, node.lineno, self.id,
                        f"thread-entry '{fn.name}' stores to shared "
                        f"{site} without a '# {self.id}: <reason>' "
                        "annotation naming the lock/Event that publishes it"))
                elif not reason:
                    findings.append(Finding(
                        src.rel, node.lineno, self.id,
                        f"'# {self.id}:' annotation on the {site} store has "
                        "no reason — name the lock/Event that publishes it"))
                if site not in declared:
                    findings.append(Finding(
                        src.rel, node.lineno, self.id,
                        f"shared store {site} in thread-entry '{fn.name}' is "
                        "not declared in SHARED_WRITES "
                        "(tools/vftlint/rules/thread_shared_state.py) — "
                        "declare the discipline deliberately"))
        return findings

    def finalize(self, root: str) -> Iterable[Finding]:
        findings: List[Finding] = []
        for rel, sites in SHARED_WRITES.items():
            if not os.path.exists(os.path.join(root, rel.replace("/", os.sep))):
                continue
            for site in sorted(set(sites) - self._observed.get(rel, set())):
                findings.append(Finding(
                    rel, 0, self.id,
                    f"SHARED_WRITES declares {site} but no thread-entry "
                    "store matches — prune the stale declaration"))
        self._observed = {}
        return findings

    @staticmethod
    def _shared_stores(fn) -> List[Tuple[str, ast.AST]]:
        """(canonical site, store node) for attribute/subscript stores in the
        thread target's body, nested defs included (they run on the thread).

        Stores to thread-private objects are exempt: a base name assigned in
        the target from a *bare-name constructor call* (``meta = Thing()``,
        ``q = Queue()``) is fresh on this thread until published. Parameters
        and names from unpacking (``handle, *job = item`` — a queue item IS
        cross-thread) stay shared; so does a constructed name that is later
        rebound from a non-fresh source."""
        private: Set[str] = set()
        for node in ast.walk(fn):
            if not isinstance(node, ast.Assign):
                continue
            fresh = (isinstance(node.value, ast.Call)
                     and isinstance(node.value.func, ast.Name))
            for target in node.targets:
                if isinstance(target, ast.Name):
                    if fresh:
                        private.add(target.id)
                    else:
                        private.discard(target.id)
        out: List[Tuple[str, ast.AST]] = []
        for node in ast.walk(fn):
            targets: List[ast.AST] = []
            if isinstance(node, ast.Assign):
                targets = list(node.targets)
            elif isinstance(node, (ast.AugAssign, ast.AnnAssign)):
                targets = [node.target]
            for target in targets:
                elts = (target.elts
                        if isinstance(target, (ast.Tuple, ast.List))
                        else [target])
                for elt in elts:
                    site = _canonical(elt)
                    if site is None:
                        continue
                    root = site.split(".", 1)[0].split("[", 1)[0]
                    if root in private:
                        continue
                    out.append((site, node))
        return out
