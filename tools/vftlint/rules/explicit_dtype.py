"""explicit-dtype: array creation in models/ and ops/ names its dtype.

``jnp.asarray([0.43, 0.39, 0.37])`` materializes at whatever the promotion
rules decide at the use site — weak-type promotion has already cost this repo
two parity hunts (the r21d KINETICS normalize constants among them). Every
``jnp.array``/``asarray``/``zeros``-family call in the numeric core must pass
a dtype, positionally or as ``dtype=``; the ``*_like`` constructors inherit
theirs and are exempt. Suppress a deliberately-promoting site with
``# explicit-dtype: <reason>``.
"""

from __future__ import annotations

import ast
from typing import Iterable, List

from ..core import Finding, Rule, SourceFile, register
from ..tracing import dotted_name

# constructor -> number of positional args at which dtype is present
# (asarray(x, dtype) → 2, full(shape, fill, dtype) → 3); None = keyword-only
# in idiomatic use (arange/linspace/eye positional dtype is buried deep)
_CREATORS = {
    "array": 2, "asarray": 2, "zeros": 2, "ones": 2, "empty": 2,
    "full": 3,
    "arange": None, "linspace": None, "eye": None,
}
# jnp only: host-side np conversions (e.g. PIL decode in ops/image.py) take
# their dtype from the source buffer, which is correct there
_MODULES = {"jnp", "jax.numpy"}


@register
class ExplicitDtypeRule(Rule):
    id = "explicit-dtype"
    title = "array constructors in the numeric core pass a dtype"
    roots = ("video_features_tpu/models", "video_features_tpu/ops")

    def check_file(self, src: SourceFile) -> Iterable[Finding]:
        findings: List[Finding] = []
        for node in ast.walk(src.tree):
            if not isinstance(node, ast.Call):
                continue
            if not isinstance(node.func, ast.Attribute):
                continue
            ctor = node.func.attr
            if ctor not in _CREATORS:
                continue
            base = dotted_name(node.func.value)
            if base not in _MODULES:
                continue
            if any(kw.arg == "dtype" for kw in node.keywords):
                continue
            dtype_pos = _CREATORS[ctor]
            if dtype_pos is not None and len(node.args) >= dtype_pos:
                continue
            if self.suppressed(src, node.lineno, findings):
                continue
            findings.append(Finding(
                src.rel, node.lineno, self.id,
                f"{base}.{ctor}() without an explicit dtype — weak-type "
                "promotion is a parity hazard; pass dtype= (or annotate why "
                "promotion is wanted here)"))
        return findings
