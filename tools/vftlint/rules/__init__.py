"""Shipped rules — importing this package registers them with the core
registry. New rules: add a module here, subclass ``Rule``, decorate with
``@register``, and import it below (docs/static-analysis.md walks through
the full checklist, fixture tests included)."""

from . import (  # noqa: F401
    blocking_under_lock,
    explicit_dtype,
    fast_registry,
    fault_barrier,
    guarded_by,
    host_sync,
    jit_purity,
    lock_order,
    recompile_hygiene,
    telemetry_schema,
    thread_shared_state,
    use_after_donate,
    wire_dtype,
)
