"""lock-order: nested lock acquisitions follow one declared total order.

Every multi-lock deadlock this stack can produce is a cycle in the
lock-acquisition graph; a total order over the named locks makes cycles
impossible BY CONSTRUCTION — as long as every nested acquisition respects
it. The model (:mod:`..locks`) observes nesting both lexically
(``with a: with b:``) and interprocedurally (``with self._lock:
self.queue.submit(...)`` — submit acquires the queue lock three frames
down), and this rule checks the resulting edges against ``LOCK_ORDER``:

- an edge that runs AGAINST the declared order is an inversion (the
  deadlock half of PR 9's "pop+register atomically under the service lock —
  lock order matches submit" review finding, mechanized);
- a cycle among observed edges is reported even when the locks involved are
  unordered — two unordered locks nested both ways deadlock all the same;
- an edge touching a lock with no LOCK_ORDER position (or no LOCK_NAMES
  name) is itself a finding: nesting is exactly the moment a lock must be
  named and ordered. Leaf locks that never nest need no position.
- a nested acquisition of a NON-reentrant lock already held is a guaranteed
  self-deadlock and is reported unconditionally.

Suppress a deliberate edge with ``# lock-order: <reason>`` on the acquiring
line. The runtime twin (:class:`..locks.LockOrderWatch`) asserts this same
table against the live daemon in tests/test_service.py and
tests/test_multimodel.py, so the declaration cannot drift from reality.
"""

from __future__ import annotations

from typing import Dict, Iterable, List, Optional, Tuple

from ..core import Finding, Rule, SourceFile, register
from .. import locks as locks_mod

# The declared total order, outermost first — today's de-facto order:
# the daemon's service lock is the outermost (ingest + serving loop), the
# scheduler's queue lock nests under it, and the observability locks
# (metrics registry, journal producer counters, stage clock) are leaves
# acquired under either. The remaining locks never nest today; they hold
# positions so the first nesting someone introduces is checked, not named
# ad hoc.
LOCK_ORDER: List[str] = [
    "service",    # serve/daemon.py ExtractionService._lock (RLock)
    "queue",      # serve/scheduler.py RequestQueue._lock
    "wal",        # serve/wal.py AdmissionLog._lock (unresolved map + degrade
                  # flag; a leaf in practice — WAL I/O runs off-lock on the
                  # writer thread — positioned under queue because submit
                  # appends after queue.submit returns)
    "registry",   # obs/metrics.py MetricsRegistry._lock
    "journal",    # obs/journal.py SpanJournal._lock (producer counters)
    "clock",      # utils/metrics.py StageClock._lock
    "resize",     # parallel/pipeline.py DecodePrefetcher._resize_lock
    "slot",       # parallel/pipeline.py decode slot['lock'] (byte cap)
    "precompile",  # extractors/flow.py ExtractFlow._precompile_lock
    "flow-steps",  # extractors/flow.py ExtractFlow._frames_steps_lock
                   # (--device_preproc per-pad-target step memo; a leaf)
    "faults",     # reliability/faults.py module _lock
]


@register
class LockOrderRule(Rule):
    id = "lock-order"
    title = "nested lock acquisitions respect the declared LOCK_ORDER"
    roots = ("video_features_tpu",)

    def __init__(self) -> None:
        self._model: Optional[locks_mod.LockModel] = None
        self._sources: Dict[str, SourceFile] = {}

    def prepare(self, root, sources, shared) -> None:
        self._model = locks_mod.shared_model(root, sources, shared)
        self._sources = sources

    # All analysis is cross-file (the graph is interprocedural), so the
    # findings are emitted from finalize; check_file contributes nothing.

    def finalize(self, root: str) -> Iterable[Finding]:
        model, self._model = self._model, None
        sources, self._sources = self._sources, {}
        if model is None:
            return []
        findings: List[Finding] = []
        # observed edge -> first witness (rel, line, via-note)
        edges: Dict[Tuple[str, str], Tuple[str, int, str]] = {}

        def add_edge(outer: str, inner: str, rel: str, line: int,
                     via: str) -> None:
            if self._suppressed_at(sources, rel, line, findings):
                return
            edges.setdefault((outer, inner), (rel, line, via))

        for fn in model.functions:
            for lock, line, held in fn.acquire_events:
                for h in held:
                    if h == lock:
                        if not model.is_reentrant(lock):
                            if not self._suppressed_at(sources, fn.rel, line,
                                                       findings):
                                findings.append(Finding(
                                    fn.rel, line, self.id,
                                    f"'{fn.qual}' re-acquires non-reentrant "
                                    f"lock '{lock}' it already holds — "
                                    "guaranteed self-deadlock"))
                        continue
                    add_edge(h, lock, fn.rel, line, "direct")
            for call, line, held in fn.call_events:
                inner = model.call_effect_locks(call, fn)
                for lock, via in inner.items():
                    for h in held:
                        if h == lock:
                            if not model.is_reentrant(lock):
                                if not self._suppressed_at(sources, fn.rel,
                                                           line, findings):
                                    findings.append(Finding(
                                        fn.rel, line, self.id,
                                        f"'{fn.qual}' holds non-reentrant "
                                        f"'{lock}' and calls '{via}' which "
                                        "may acquire it again — potential "
                                        "self-deadlock"))
                            continue
                        add_edge(h, lock, fn.rel, line, f"via {via}()")

        rank = {name: i for i, name in enumerate(LOCK_ORDER)}
        for (outer, inner), (rel, line, via) in sorted(edges.items()):
            missing = [l for l in (outer, inner) if l not in rank]
            if missing:
                for lock in missing:
                    findings.append(Finding(
                        rel, line, self.id,
                        f"nested acquisition involves lock '{lock}' with no "
                        "LOCK_ORDER position — name it in LOCK_NAMES "
                        "(tools/vftlint/locks.py) and order it in LOCK_ORDER "
                        "(tools/vftlint/rules/lock_order.py)"))
                continue
            if rank[outer] > rank[inner]:
                findings.append(Finding(
                    rel, line, self.id,
                    f"lock-order inversion: '{inner}' acquired while "
                    f"holding '{outer}' ({via}) — LOCK_ORDER declares "
                    f"'{inner}' before '{outer}'"))
        findings.extend(self._cycles(edges))
        findings.extend(self._stale_order(root, model))
        return findings

    def _suppressed_at(self, sources: Dict[str, SourceFile], rel: str,
                       line: int, findings: List[Finding]) -> bool:
        src = sources.get(rel)
        return src is not None and self.suppressed(src, line, findings)

    def _cycles(self, edges) -> Iterable[Finding]:
        """Cycles in the observed graph (deadlock risk even among locks
        LOCK_ORDER does not rank)."""
        graph: Dict[str, List[str]] = {}
        for outer, inner in edges:
            graph.setdefault(outer, []).append(inner)
        findings: List[Finding] = []
        reported = set()

        def dfs(node: str, stack: List[str], on_stack: set) -> None:
            on_stack.add(node)
            stack.append(node)
            for nxt in graph.get(node, ()):
                if nxt in on_stack:
                    cycle = tuple(stack[stack.index(nxt):]) + (nxt,)
                    key = frozenset(cycle)
                    if key not in reported:
                        reported.add(key)
                        rel, line, _ = edges[(node, nxt)]
                        findings.append(Finding(
                            rel, line, self.id,
                            "lock-acquisition cycle "
                            f"{' -> '.join(cycle)} — deadlock risk; break "
                            "the cycle or re-order the acquisitions"))
                else:
                    dfs(nxt, stack, on_stack)
            stack.pop()
            on_stack.discard(node)

        for node in sorted(graph):
            dfs(node, [], set())
        return findings

    def _stale_order(self, root: str,
                     model: locks_mod.LockModel) -> Iterable[Finding]:
        """LOCK_ORDER entries whose lock no longer exists (only checked when
        the declaring file is present in this root, so fixture trees are not
        blamed for the repo's table)."""
        import os

        findings: List[Finding] = []
        canon_by_name = {v: k for k, v in locks_mod.LOCK_NAMES.items()}
        for name in LOCK_ORDER:
            if model.site_named(name) is not None:
                continue
            canonical = canon_by_name.get(name)
            if canonical is None:
                continue
            rel = canonical.split(":", 1)[0]
            if os.path.exists(os.path.join(root, rel.replace("/", os.sep))):
                findings.append(Finding(
                    rel, 0, self.id,
                    f"LOCK_ORDER names '{name}' ({canonical}) but no such "
                    "lock is created there — prune the stale entry"))
        return findings
