"""fast-registry: every test module is deliberately tiered.

The suite has three tiers (tests/conftest.py): ``fast`` (module listed in
``_FAST_MODULES`` — pre-commit signal), ``slow`` (module-level
``pytestmark = pytest.mark.slow`` — parity/e2e, excluded by the pyproject
default ``-m 'not slow'``), and the default tier in between. A new test
module silently landing in the default tier inflates the tier-1 wall-clock
budget (870 s timeout, docs/budgets.md) without anyone choosing that — so
membership is declared:

1. listed in conftest ``_FAST_MODULES``; or
2. module-level ``pytestmark = pytest.mark.slow``; or
3. listed in ``DEFAULT_TIER`` below AND carrying a
   ``# fast-registry: <reason>`` comment in the file saying why it sits in
   the default tier.
"""

from __future__ import annotations

import ast
import os
from typing import Dict, Iterable, List, Optional, Set

from ..core import Finding, Rule, SourceFile, register

# Deliberate default-tier modules: too compile-heavy for the fast tier, too
# load-bearing for slow-only CI. Each file carries a matching annotation.
DEFAULT_TIER: Dict[str, str] = {
    "test_bench_record": "bench record/merge logic drives jitted extractors",
    "test_decode_pool": "real-sleep concurrency tests on the decode pool",
    "test_device_preproc": "device-preproc parity over real-model compiles",
    "test_fault_injection": "e2e extraction under injected faults (compiles)",
    "test_flow_bf16": "bf16 drift measurement over flow compiles",
    "test_flow_frames": "shared-frame flow forward parity (flow compiles)",
    "test_kernels": "kernel parity vs torch mirrors",
    "test_metrics": "stage-clock tests with real sleeps",
    "test_multihost": "loopback two-process jax.distributed init",
    "test_packer_models": "real-model packed parity (jit compiles)",
    "test_paged": "paged dispatch parity (jit compiles)",
    "test_resnet": "resnet50 forward parity (heavy compile)",
    "test_segmented_decode": "real-sleep pool concurrency + e2e parity runs",
    "test_vggish": "vggish DSP + forward parity",
    "test_weights_store": "checkpoint store roundtrips",
    "test_windows": "pre-dates the fast registry; re-tier on the next sweep",
}


def _fast_modules(conftest: SourceFile) -> Set[str]:
    for node in ast.walk(conftest.tree):
        if not isinstance(node, ast.Assign):
            continue
        if not any(isinstance(t, ast.Name) and t.id == "_FAST_MODULES"
                   for t in node.targets):
            continue
        if isinstance(node.value, (ast.Set, ast.List, ast.Tuple)):
            return {e.value for e in node.value.elts
                    if isinstance(e, ast.Constant) and isinstance(e.value, str)}
    return set()


def _slow_marked(src: SourceFile) -> bool:
    """Module-level ``pytestmark = pytest.mark.slow`` (or a list holding it)."""
    for node in src.tree.body:
        if not isinstance(node, ast.Assign):
            continue
        if not any(isinstance(t, ast.Name) and t.id == "pytestmark"
                   for t in node.targets):
            continue
        marks = (node.value.elts
                 if isinstance(node.value, (ast.List, ast.Tuple))
                 else [node.value])
        for mark in marks:
            if isinstance(mark, ast.Attribute) and mark.attr == "slow":
                return True
    return False


@register
class FastRegistryRule(Rule):
    id = "fast-registry"
    title = "test modules declare their tier (fast / slow / default)"
    roots = ("tests",)

    def __init__(self) -> None:
        self._modules: Dict[str, SourceFile] = {}
        self._conftest: Optional[SourceFile] = None

    def wants(self, rel: str) -> bool:
        name = os.path.basename(rel)
        return name == "conftest.py" or (
            name.startswith("test_") and name.endswith(".py"))

    def annotation_live(self, src: SourceFile, line: int) -> bool:
        # this rule's grammar is file-level, not line-level: the comment
        # declares why a DEFAULT_TIER module sits in the default tier
        # (finalize reads src.comments directly, so the consumed-set default
        # never sees it). Live iff the module is still declared DEFAULT_TIER
        # — a module that leaves the tier makes its comment stale.
        name = os.path.basename(src.rel)
        return name.endswith(".py") and name[:-3] in DEFAULT_TIER

    def check_file(self, src: SourceFile) -> Iterable[Finding]:
        name = os.path.basename(src.rel)
        if name == "conftest.py":
            self._conftest = src
        else:
            self._modules[name[:-3]] = src
        return ()

    def finalize(self, root: str) -> Iterable[Finding]:
        findings: List[Finding] = []
        modules, conftest = self._modules, self._conftest
        self._modules, self._conftest = {}, None
        if conftest is None:
            if modules:  # a tests tree without the registry at all
                findings.append(Finding(
                    "tests/conftest.py", 0, self.id,
                    "no conftest.py with _FAST_MODULES found — the fast "
                    "registry is missing"))
            return findings
        fast = _fast_modules(conftest)
        for module, src in sorted(modules.items()):
            if module in fast:
                continue
            if _slow_marked(src):
                continue
            if module in DEFAULT_TIER:
                marker = f"{self.id}:"
                reasons = [c.split(marker, 1)[1].strip()
                           for c in src.comments.values() if marker in c]
                if any(reasons):
                    continue
                if reasons:  # annotation present but reasonless
                    findings.append(Finding(
                        src.rel, 1, self.id,
                        f"'# {self.id}:' comment in '{module}' has no "
                        "reason — say why it sits in the default tier"))
                else:
                    findings.append(Finding(
                        src.rel, 1, self.id,
                        f"'{module}' is declared DEFAULT_TIER but carries no "
                        f"'# {self.id}: <reason>' comment — annotate why it "
                        "sits in the default tier"))
                continue
            findings.append(Finding(
                src.rel, 1, self.id,
                f"'{module}' is in no tier: add it to conftest "
                "_FAST_MODULES, mark it pytestmark = pytest.mark.slow, or "
                "declare it in DEFAULT_TIER "
                "(tools/vftlint/rules/fast_registry.py) with an in-file "
                f"'# {self.id}: <reason>' comment"))
        for module in sorted(set(DEFAULT_TIER) - set(modules)):
            findings.append(Finding(
                f"tests/{module}.py", 0, self.id,
                f"DEFAULT_TIER declares '{module}' but no such test module "
                "exists — prune the stale entry"))
        for module in sorted(set(DEFAULT_TIER) & fast):
            findings.append(Finding(
                f"tests/{module}.py", 0, self.id,
                f"'{module}' is both in _FAST_MODULES and DEFAULT_TIER — "
                "pick one tier"))
        return findings
