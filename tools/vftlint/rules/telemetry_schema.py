"""telemetry-schema: emitted events match the documented catalogue.

The journal is append-only and additive — consumers must ignore unknown
events — which is exactly why drift is silent: an emit site renamed or
grown a field keeps working, the docs and the exporter just stop telling
the truth (it already happened once; review caught it). This rule makes the
three-way contract mechanical:

1. **Events** — every journal ``emit``/``span``/``begin``/``end`` event
   name, from every emit site in the package, must appear in the
   ``docs/observability.md`` event catalogue. Sites are collected through
   the declared wrappers (``Extractor._emit``/``_span`` inject ``model``,
   ``ExtractionService._emit``, the scheduler's ``_note_queued``), which
   are *discovered*, not hardcoded: any package function that forwards one
   of its parameters as the event name into a journal call (directly or
   through another wrapper) is a wrapper, and its call sites are resolved
   with the shared literal-string flow (:mod:`tools.vftlint.dataflow`) —
   so ``_note_queued(job, "video_requeued")`` resolves and an event name
   built from runtime data is a finding (unresolvable = uncheckable).
   ``obs/journal.py`` itself is the primitive layer (its span machinery
   builds ``<name>_start``/``_end`` strings) and is skipped, except its
   ``journal_open``/``journal_close`` record literals.
2. **Exporter** — ``obs/export.py``'s pairing event names (the ``name ==
   "video_popped"``-style literals), derived slice names (``slice_event``
   literals), and ``_META_EVENTS`` must all be documented.
3. **Stats schema** — the daemon ``stats`` op's top-level keys (and the
   sub-keys of statically enumerable groups: inline dict literals and
   one-hop ``self._method()`` dict returns) must match the schema-1 table
   in ``docs/serving.md``, in *both* directions — the table is the external
   scraper's contract, so a stale documented field is as bad as an
   undocumented emitted one.

Per-event fields are checked as a subset of the catalogue row's backticked
fields (plus the wrapper's injected fields and the implicit ``span``); a
row with no backticked fields is a wildcard. When the tree has no emit
sites and no stats op, the rule is silent — fixture trees without docs are
not drift.

Suppress with ``# telemetry-schema: <reason>``.
"""

from __future__ import annotations

import ast
import os
import re
from typing import Dict, FrozenSet, Iterable, List, Optional, Set, Tuple

from ..core import Finding, Rule, SourceFile, register
from ..dataflow import StringFlow, literal_strings, walk_no_defs
from ..tracing import dotted_name

_EMIT_KINDS = {"emit", "span", "begin", "end"}
_OBS_DOC = "docs/observability.md"
_SERVE_DOC = "docs/serving.md"
_JOURNAL_MOD = "video_features_tpu/obs/journal.py"
_EXPORT_MOD = "video_features_tpu/obs/export.py"
_DAEMON_MOD = "video_features_tpu/serve/daemon.py"

_BACKTICK = re.compile(r"`([^`]+)`")


def _receiver_is_journal(func: ast.AST) -> bool:
    if not isinstance(func, ast.Attribute):
        return False
    recv = (dotted_name(func.value) or "").lower()
    return "journal" in recv


class _Wrapper:
    __slots__ = ("rel", "name", "event_pos", "injected", "kind", "line")

    def __init__(self, rel: str, name: str, event_pos: int,
                 injected: FrozenSet[str], kind: str, line: int):
        self.rel = rel
        self.name = name
        self.event_pos = event_pos  # positional index at CALL sites
        self.injected = injected
        self.kind = kind            # emit | span | begin | end
        self.line = line


class _Site:
    __slots__ = ("rel", "line", "events", "kind", "fields", "src")

    def __init__(self, rel: str, line: int, events: FrozenSet[str],
                 kind: str, fields: FrozenSet[str], src: SourceFile):
        self.rel = rel
        self.line = line
        self.events = events
        self.kind = kind
        self.fields = fields  # literal kwargs ∪ wrapper-injected
        self.src = src

    def event_names(self) -> Iterable[str]:
        """The journal record names this site produces."""
        for ev in sorted(self.events):
            if self.kind == "emit":
                yield ev
            elif self.kind == "begin":
                yield f"{ev}_start"
            elif self.kind == "end":
                yield f"{ev}_end"
            else:  # span: both edges
                yield f"{ev}_start"
                yield f"{ev}_end"


def _parse_catalogue(text: str) -> Dict[str, Tuple[Optional[Set[str]], int]]:
    """event name -> (documented fields | None = wildcard, doc line)."""
    out: Dict[str, Tuple[Optional[Set[str]], int]] = {}
    in_section = False
    for lineno, line in enumerate(text.splitlines(), 1):
        if line.startswith("### Event catalogue"):
            in_section = True
            continue
        if in_section and (line.startswith("## ") or line.startswith("### ")):
            break
        if not in_section or not line.startswith("|"):
            continue
        cells = [c.strip() for c in line.strip().strip("|").split("|")]
        if len(cells) < 3:
            continue
        names = _BACKTICK.findall(cells[0])
        field_tokens = _BACKTICK.findall(cells[2])
        fields = set(field_tokens) if field_tokens else None
        for name in names:
            out[name] = (fields, lineno)
    return out


def _parse_stats_table(
        text: str) -> Tuple[Dict[str, int], Dict[str, Optional[Set[str]]]]:
    """(documented top-level key -> doc line,
    top-level key -> first-level sub keys | None = not enumerable)."""
    tops: Dict[str, int] = {}
    subs: Dict[str, Optional[Set[str]]] = {}
    in_section = False
    for lineno, line in enumerate(text.splitlines(), 1):
        if "`stats` payload" in line and line.startswith("#"):
            in_section = True
            continue
        if in_section and line.startswith("## "):
            break
        if not in_section or not line.startswith("|"):
            continue
        cells = [c.strip() for c in line.strip().strip("|").split("|")]
        if not cells:
            continue
        for token in _BACKTICK.findall(cells[0]):
            top, sep, rest = token.partition(".")
            top = top.strip()
            if not top or " " in top:
                continue
            tops.setdefault(top, lineno)
            if not sep:
                continue
            first = rest.split(".", 1)[0].strip()
            if first.startswith("{") :
                inner = rest[rest.index("{") + 1:rest.rindex("}")]
                names = {s.strip() for s in inner.split(",") if s.strip()}
                cur = subs.get(top)
                subs[top] = (cur or set()) | names
            elif first.startswith("<"):
                subs[top] = None  # keyed by runtime name: not enumerable
            elif first:
                cur = subs.get(top)
                if top not in subs or cur is not None:
                    subs[top] = (cur or set()) | {first}
    return tops, subs


@register
class TelemetrySchemaRule(Rule):
    id = "telemetry-schema"
    title = "journal events/fields and stats schema match the docs"
    roots = ("video_features_tpu",)

    def prepare(self, root: str, sources, shared) -> None:
        self._root = root
        self._sources = {rel: src for rel, src in sources.items()
                         if rel.startswith("video_features_tpu/")
                         and getattr(src, "tree", None) is not None}
        self._discover_wrappers()

    # -- wrapper discovery ---------------------------------------------------

    def _classify(self, call: ast.Call, rel: str):
        """(kind, event_pos, injected) when ``call`` emits — a direct
        journal call or a call to a discovered wrapper — else None.
        Same-file wrappers win on a name collision (``_emit`` exists on
        both Extractor and ExtractionService); across files the injected
        sets intersect — under-approximating emitted fields can only
        under-check, never false-positive."""
        func = call.func
        if (isinstance(func, ast.Attribute) and func.attr in _EMIT_KINDS
                and _receiver_is_journal(func)):
            return func.attr, 0, frozenset()
        last = None
        if isinstance(func, ast.Attribute):
            last = func.attr
        elif isinstance(func, ast.Name):
            last = func.id
        infos = self._wrappers.get(last or "")
        if not infos:
            return None
        local = [i for i in infos if i.rel == rel]
        if local:
            infos = local
        injected: Optional[FrozenSet[str]] = None
        for info in infos:
            injected = (info.injected if injected is None
                        else injected & info.injected)
        return infos[0].kind, infos[0].event_pos, injected or frozenset()

    def _discover_wrappers(self) -> None:
        self._wrappers: Dict[str, List[_Wrapper]] = {}
        seen: Set[Tuple[str, int]] = set()
        changed = True
        while changed:
            changed = False
            for rel, src in sorted(self._sources.items()):
                if rel in (_JOURNAL_MOD, _EXPORT_MOD):
                    continue
                if not self._may_emit(src):
                    continue
                for fn in ast.walk(src.tree):
                    if not isinstance(fn, (ast.FunctionDef,
                                           ast.AsyncFunctionDef)):
                        continue
                    params = [a.arg for a in fn.args.args]
                    if not params:
                        continue
                    self_offset = 1 if params[0] in ("self", "cls") else 0
                    for stmt in fn.body:
                        if isinstance(stmt, (ast.FunctionDef,
                                             ast.AsyncFunctionDef,
                                             ast.ClassDef)):
                            continue
                        for call in walk_no_defs(stmt):
                            if not isinstance(call, ast.Call):
                                continue
                            info = self._classify(call, rel)
                            if info is None:
                                continue
                            kind, pos, injected = info
                            if pos >= len(call.args):
                                continue
                            arg = call.args[pos]
                            if not (isinstance(arg, ast.Name)
                                    and arg.id in params):
                                continue
                            key = (rel, fn.lineno)
                            if key in seen:
                                continue
                            seen.add(key)
                            own = frozenset(
                                kw.arg for kw in call.keywords
                                if kw.arg is not None)
                            self._wrappers.setdefault(fn.name, []).append(
                                _Wrapper(rel, fn.name,
                                         params.index(arg.id) - self_offset,
                                         own | injected, kind, fn.lineno))
                            changed = True

    def _wrapper_params(self) -> Set[Tuple[str, int]]:
        return {(w.rel, w.line) for ws in self._wrappers.values()
                for w in ws}

    def _may_emit(self, src: SourceFile) -> bool:
        """Cheap text pre-filter: a file with no 'journal' token and no
        known wrapper name cannot contain an emit site or define a new
        wrapper (text containment over-approximates the AST calls, so the
        fixpoint and the site sweep stay exact)."""
        text = src.text
        if "journal" in text:
            return True
        return any(name in text for name in self._wrappers)

    # -- site collection -----------------------------------------------------

    def _collect_sites(self) -> Tuple[List[_Site], List[Finding]]:
        sites: List[_Site] = []
        findings: List[Finding] = []
        wrapper_defs = self._wrapper_params()
        for rel, src in sorted(self._sources.items()):
            if rel == _EXPORT_MOD:
                continue
            if rel == _JOURNAL_MOD:
                self._collect_journal_literals(rel, src, sites)
                continue
            if not self._may_emit(src):
                continue
            defs = [n for n in ast.walk(src.tree)
                    if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))]
            nested = {sub for fn in defs for sub in ast.walk(fn)
                      if sub is not fn and isinstance(
                          sub, (ast.FunctionDef, ast.AsyncFunctionDef))}
            for fn in defs:
                if fn in nested:
                    continue
                self._scan_fn(rel, src, fn, wrapper_defs, sites, findings)
        return sites, findings

    def _scan_fn(self, rel: str, src: SourceFile, fn, wrapper_defs,
                 sites: List[_Site], findings: List[Finding]) -> None:
        params = {a.arg for a in fn.args.args}
        is_wrapper_def = (rel, fn.lineno) in wrapper_defs

        def on_call(call: ast.Call, env) -> None:
            info = self._classify(call, rel)
            if info is None:
                return
            kind, pos, injected = info
            if pos >= len(call.args):
                return
            arg = call.args[pos]
            events = (frozenset({arg.value})
                      if isinstance(arg, ast.Constant)
                      and isinstance(arg.value, str)
                      else literal_strings(arg, env))
            if events is None:
                if (isinstance(arg, ast.Name) and arg.id in params
                        and is_wrapper_def):
                    return  # the wrapper's own forwarding call
                if self.suppressed(src, call.lineno, findings):
                    return
                findings.append(Finding(
                    rel, call.lineno, self.id,
                    "event name is not statically resolvable — emit a "
                    "literal (or declare a forwarding wrapper) so the "
                    f"{_OBS_DOC} catalogue stays checkable"))
                return
            fields = frozenset(kw.arg for kw in call.keywords
                               if kw.arg is not None) | injected
            sites.append(_Site(rel, call.lineno, events, kind, fields, src))

        StringFlow(on_call).scan_block(fn.body)

    def _collect_journal_literals(self, rel: str, src: SourceFile,
                                  sites: List[_Site]) -> None:
        """journal_open/journal_close are written as raw record dicts by the
        writer thread — the one place an event is born outside emit()."""
        for node in ast.walk(src.tree):
            if not isinstance(node, ast.Dict):
                continue
            keys = {}
            for k, v in zip(node.keys, node.values):
                if isinstance(k, ast.Constant) and isinstance(k.value, str):
                    keys[k.value] = v
            ev = keys.get("event")
            if not (isinstance(ev, ast.Constant)
                    and isinstance(ev.value, str)):
                continue
            fields = frozenset(k for k in keys if k not in ("ts", "event"))
            sites.append(_Site(rel, node.lineno, frozenset({ev.value}),
                               "emit", fields, src))

    # -- checks --------------------------------------------------------------

    def finalize(self, root: str) -> Iterable[Finding]:
        findings: List[Finding] = []
        sites, findings_ = self._collect_sites()
        findings.extend(findings_)
        self._check_catalogue(root, sites, findings)
        self._check_stats(root, findings)
        return findings

    def _read_doc(self, root: str, rel: str) -> Optional[str]:
        path = os.path.join(root, rel.replace("/", os.sep))
        try:
            with open(path, encoding="utf-8") as f:
                return f.read()
        except OSError:
            return None

    def _check_catalogue(self, root: str, sites: List[_Site],
                         findings: List[Finding]) -> None:
        export_names = self._export_names()
        if not sites and not export_names:
            return
        text = self._read_doc(root, _OBS_DOC)
        if text is None:
            findings.append(Finding(
                _OBS_DOC, 0, self.id,
                "journal emit sites exist but the event catalogue doc is "
                "missing"))
            return
        catalogue = _parse_catalogue(text)
        for site in sites:
            for name in site.event_names():
                entry = catalogue.get(name)
                if entry is None:
                    if self.suppressed(site.src, site.line, findings):
                        continue
                    findings.append(Finding(
                        site.rel, site.line, self.id,
                        f"event '{name}' is not in the {_OBS_DOC} event "
                        "catalogue — the journal is additive; document the "
                        "row (event + fields) before emitting it"))
                    continue
                doc_fields, _ = entry
                if doc_fields is None:
                    continue
                allowed = set(doc_fields) | {"span"}
                extra = sorted(site.fields - allowed)
                if extra:
                    if self.suppressed(site.src, site.line, findings):
                        continue
                    findings.append(Finding(
                        site.rel, site.line, self.id,
                        f"event '{name}' emits undocumented field(s) "
                        f"{', '.join(extra)} — update the {_OBS_DOC} "
                        "catalogue row (fields are additive but must be "
                        "listed)"))
        for name, line in sorted(export_names.items()):
            if name not in catalogue and not self._doc_mentions(text, name):
                findings.append(Finding(
                    _EXPORT_MOD, line, self.id,
                    f"exporter references '{name}' which the {_OBS_DOC} "
                    "catalogue/doc does not mention — pairing and derived "
                    "slice names are part of the documented contract"))

    @staticmethod
    def _doc_mentions(text: str, name: str) -> bool:
        return f"`{name}`" in text

    def _export_names(self) -> Dict[str, int]:
        """Event/slice names the exporter hard-codes: pairing literals in
        comparisons against the record name, ``slice_event`` literal first
        args, and ``_META_EVENTS``."""
        src = self._sources.get(_EXPORT_MOD)
        if src is None:
            return {}
        names: Dict[str, int] = {}
        for node in ast.walk(src.tree):
            if isinstance(node, ast.Compare):
                left = node.left
                if not (isinstance(left, ast.Name) and left.id == "name"):
                    continue
                for comp in node.comparators:
                    elts = (comp.elts if isinstance(comp, (ast.Tuple,
                                                           ast.List, ast.Set))
                            else [comp])
                    for elt in elts:
                        if (isinstance(elt, ast.Constant)
                                and isinstance(elt.value, str)):
                            names.setdefault(elt.value, elt.lineno)
            elif isinstance(node, ast.Call):
                fname = dotted_name(node.func) or ""
                if (fname.rsplit(".", 1)[-1] == "slice_event" and node.args
                        and isinstance(node.args[0], ast.Constant)
                        and isinstance(node.args[0].value, str)):
                    names.setdefault(node.args[0].value, node.lineno)
            elif isinstance(node, ast.Assign):
                targets = [t.id for t in node.targets
                           if isinstance(t, ast.Name)]
                if "_META_EVENTS" in targets and isinstance(
                        node.value, (ast.Set, ast.Tuple, ast.List)):
                    for elt in node.value.elts:
                        if (isinstance(elt, ast.Constant)
                                and isinstance(elt.value, str)):
                            names.setdefault(elt.value, elt.lineno)
        return names

    # -- stats schema --------------------------------------------------------

    def _check_stats(self, root: str, findings: List[Finding]) -> None:
        src = self._sources.get(_DAEMON_MOD)
        if src is None:
            return
        stats_fn = None
        for node in ast.walk(src.tree):
            if (isinstance(node, ast.FunctionDef) and node.name == "stats"):
                stats_fn = node
                break
        if stats_fn is None:
            return
        payload = None
        for node in walk_no_defs(ast.Module(body=stats_fn.body,
                                            type_ignores=[])):
            if isinstance(node, ast.Dict):
                keys = [k.value for k in node.keys
                        if isinstance(k, ast.Constant)]
                if "schema" in keys:
                    payload = node
                    break
        if payload is None:
            return
        emitted: Dict[str, ast.AST] = {}
        for k, v in zip(payload.keys, payload.values):
            if isinstance(k, ast.Constant) and isinstance(k.value, str):
                emitted[k.value] = v
        text = self._read_doc(root, _SERVE_DOC)
        if text is None:
            findings.append(Finding(
                _SERVE_DOC, 0, self.id,
                "the stats op exists but its schema doc is missing"))
            return
        doc_tops, doc_subs = _parse_stats_table(text)
        if not doc_tops:
            findings.append(Finding(
                _SERVE_DOC, 0, self.id,
                "no `stats` payload schema table found — the versioned "
                "payload needs its field-tree contract documented"))
            return
        for key, value in sorted(emitted.items()):
            if key not in doc_tops:
                if self.suppressed(src, value.lineno, findings):
                    continue
                findings.append(Finding(
                    _DAEMON_MOD, value.lineno, self.id,
                    f"stats op emits undocumented top-level field '{key}' "
                    f"— the schema-1 table in {_SERVE_DOC} is the scraper "
                    "contract"))
        for key, line in sorted(doc_tops.items()):
            if key not in emitted:
                findings.append(Finding(
                    _SERVE_DOC, line, self.id,
                    f"schema table documents '{key}' but the stats op no "
                    "longer emits it — prune or restore (a silent removal "
                    "is a schema break)"))
        for key, value in sorted(emitted.items()):
            sub_emitted = self._enumerate_subkeys(src, value)
            sub_doc = doc_subs.get(key)
            if sub_emitted is None or sub_doc is None:
                continue
            for sub in sorted(sub_emitted - sub_doc):
                if self.suppressed(src, value.lineno, findings):
                    continue
                findings.append(Finding(
                    _DAEMON_MOD, value.lineno, self.id,
                    f"stats field '{key}.{sub}' is not in the "
                    f"{_SERVE_DOC} schema table"))
            for sub in sorted(sub_doc - sub_emitted):
                findings.append(Finding(
                    _SERVE_DOC, doc_tops[key], self.id,
                    f"schema table documents '{key}.{sub}' but the stats "
                    "op does not emit it"))

    def _enumerate_subkeys(self, src: SourceFile,
                           value: ast.AST) -> Optional[Set[str]]:
        """First-level sub keys when statically enumerable: an inline dict
        literal, or a one-hop ``self._method()`` whose single return is a
        dict literal."""
        if isinstance(value, ast.Dict):
            if any(k is None or not isinstance(k, ast.Constant)
                   for k in value.keys):
                return None
            return {k.value for k in value.keys
                    if isinstance(k.value, str)}
        if (isinstance(value, ast.Call) and not value.args
                and not value.keywords
                and isinstance(value.func, ast.Attribute)):
            mname = value.func.attr
            for node in ast.walk(src.tree):
                if (isinstance(node, ast.FunctionDef)
                        and node.name == mname):
                    rets = [r for r in ast.walk(node)
                            if isinstance(r, ast.Return)
                            and r.value is not None]
                    if len(rets) == 1 and isinstance(rets[0].value,
                                                     ast.Dict):
                        return self._enumerate_subkeys(src, rets[0].value)
                    return None
        return None
