"""use-after-donate: a donated device buffer is dead after dispatch.

``sharded_apply`` forwards ``donate_argnums`` into ``jax.jit`` so the paged
row table's device buffer is recycled in place (PR 13: ``MeshRunner.jit_paged``
donates argnum 2). Donation is a transfer of ownership: after the donating
call returns, the *input* buffer's storage belongs to the output — reading
it, returning it, or re-staging it is undefined behavior that XLA only
sometimes reports (and on TPU usually manifests as silently corrupt rows).

Two checks, both riding the shared line-order pass
(:mod:`tools.vftlint.dataflow`):

1. **Use after donation** — within a function, a *device-tagged* name passed
   at a donated argnum position of a donating callable must not be read on
   any subsequent path before reassignment. Donating callables are resolved
   through the wiring: direct ``jax.jit(..., donate_argnums=(...))`` /
   ``sharded_apply(..., donate_argnums=(...))`` calls, plus package wrapper
   functions that forward their own parameter into such a call with a
   literal donation (``MeshRunner.jit_paged``) — discovered in ``prepare()``
   so findings name the via-chain. A donation inside a loop whose buffer is
   never re-staged in the loop body is flagged too: the second iteration
   would dispatch an already-donated buffer.
2. **In/out pair** — every ``donate_argnums`` declaration must name a
   parameter the wrapped function returns (the shape/dtype-identical in/out
   pair XLA needs to alias the buffers; ``paged_program``'s ``paged`` passes
   the row table through verbatim). Wrapped functions are resolved by name
   within the module, one helper hop deep (``paged_program(forward)``
   resolves to the nested ``paged`` it returns).

Only *device* values (results of ``runner.put``/``self._put``/
``jax.device_put``/``prefetch_to_device``/step calls/donating calls) are
tracked at donated positions: passing a host ``numpy`` array donates the
transient device *copy*, and the host original stays valid (the packer's
row-table path relies on this).

Suppress a deliberate exception with ``# use-after-donate: <reason>``.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterable, List, Optional, Set, Tuple

from ..core import Finding, Rule, SourceFile, register
from ..dataflow import LineOrderScanner, walk_no_defs
from ..tracing import dotted_name

# call names that CREATE a jitted callable and accept donate_argnums directly
_BASE_FN_ARG = {"jit": 0, "pjit": 0, "sharded_apply": 1}

# calls whose RESULT is a fresh device value (reading it later is fine; and
# passing `f(x)` at a donated position donates f's result, not any name)
_DEVICE_PRODUCERS = {"put", "put_replicated", "_put", "_put_replicated",
                     "device_put", "prefetch_to_device", "_stage_rows"}


def _literal_argnums(call: ast.Call) -> Optional[Tuple[int, ...]]:
    """The literal ``donate_argnums`` of ``call``, or None when absent or
    not statically resolvable (e.g. forwarded from an enclosing parameter —
    that's the wiring function itself, checked at its call sites)."""
    for kw in call.keywords:
        if kw.arg != "donate_argnums":
            continue
        node = kw.value
        if isinstance(node, (ast.Tuple, ast.List)):
            out = []
            for elt in node.elts:
                if not (isinstance(elt, ast.Constant)
                        and isinstance(elt.value, int)):
                    return None
                out.append(elt.value)
            return tuple(out)
        if isinstance(node, ast.Constant) and isinstance(node.value, int):
            return (node.value,)
        return None
    return None


def _donating_base_call(call: ast.Call):
    """(argnums, fn_arg_index, via) for a direct donating constructor call."""
    name = dotted_name(call.func) or ""
    last = name.rsplit(".", 1)[-1]
    if last not in _BASE_FN_ARG:
        return None
    argnums = _literal_argnums(call)
    if not argnums:
        return None
    return argnums, _BASE_FN_ARG[last], f"{name}(donate_argnums={argnums})"


class _DonateSpec:
    """A callable that donates: which argnums, and the wiring chain that
    makes it so (for the finding message)."""

    def __init__(self, argnums: Tuple[int, ...], via: str):
        self.argnums = argnums
        self.via = via


class _Scanner(LineOrderScanner):
    """Per-function donation tracking: ``donating`` (name → spec),
    ``device`` (device-tagged names), ``donated`` (name → (line, via))."""

    def __init__(self, rule: "UseAfterDonateRule", src: SourceFile,
                 findings: List[Finding]):
        self.rule = rule
        self.src = src
        self.findings = findings
        self.donating: Dict[str, _DonateSpec] = {}
        self.device: Set[str] = set()
        self.donated: Dict[str, Tuple[int, str]] = {}
        self._loops: List[Tuple[Set[str], Set[str]]] = []  # (pre-donated, assigned-in-loop)

    # -- state protocol -----------------------------------------------------

    def snapshot(self):
        return (dict(self.donating), set(self.device), dict(self.donated))

    def restore(self, token) -> None:
        self.donating = dict(token[0])
        self.device = set(token[1])
        self.donated = dict(token[2])

    def merged(self, tokens):
        donating: Dict[str, _DonateSpec] = {}
        device: Set[str] = set()
        donated: Dict[str, Tuple[int, str]] = {}
        for d, dev, don in tokens:
            donating.update(d)
            device |= dev
            donated.update(don)
        return (donating, device, donated)

    # -- helpers ------------------------------------------------------------

    def _spec_for_call(self, call: ast.Call) -> Optional[_DonateSpec]:
        """Spec if ``call`` invokes a donating callable (a tracked local
        name, or a known wiring wrapper like ``runner.jit_paged``)."""
        if isinstance(call.func, ast.Name):
            return self.donating.get(call.func.id)
        name = dotted_name(call.func) or ""
        last = name.rsplit(".", 1)[-1]
        return self.rule.wrappers.get(last)

    def _constructed_spec(self, value: ast.AST) -> Optional[_DonateSpec]:
        """Spec when ``value`` constructs a donating callable."""
        if not isinstance(value, ast.Call):
            return None
        base = _donating_base_call(value)
        if base is not None:
            argnums, _, via = base
            return _DonateSpec(argnums, via)
        name = dotted_name(value.func) or ""
        wrapper = self.rule.wrappers.get(name.rsplit(".", 1)[-1])
        if wrapper is not None:
            return wrapper
        return None

    def _is_device_value(self, value: ast.AST) -> bool:
        if isinstance(value, ast.Name):
            return value.id in self.device
        if isinstance(value, ast.Call):
            name = dotted_name(value.func) or ""
            if name.rsplit(".", 1)[-1] in _DEVICE_PRODUCERS:
                return True
            return self._spec_for_call(value) is not None
        return False

    # -- checks -------------------------------------------------------------

    def _check_reads(self, node: ast.AST) -> None:
        if not self.donated:
            return
        for sub in walk_no_defs(node):
            if (isinstance(sub, ast.Name) and isinstance(sub.ctx, ast.Load)
                    and sub.id in self.donated):
                line, via = self.donated[sub.id]
                # avoid double-reporting every read of the same donation
                del self.donated[sub.id]
                if self.rule.suppressed(self.src, sub.lineno, self.findings):
                    continue
                self.findings.append(Finding(
                    self.src.rel, sub.lineno, self.rule.id,
                    f"'{sub.id}' is read after its buffer was donated at "
                    f"line {line} (via {via}) — a donated input's storage "
                    "belongs to the output after dispatch; re-stage a fresh "
                    "copy or drop the read"))

    def _record_donations(self, node: ast.AST) -> None:
        for call in walk_no_defs(node):
            if not isinstance(call, ast.Call):
                continue
            spec = self._spec_for_call(call)
            if spec is None:
                continue
            for argnum in spec.argnums:
                if argnum >= len(call.args):
                    continue
                arg = call.args[argnum]
                if (isinstance(arg, ast.Name)
                        and arg.id in self.device):
                    self.donated[arg.id] = (call.lineno, spec.via)

    # -- walk hooks ---------------------------------------------------------

    def visit_expr(self, expr: ast.AST) -> None:
        self._check_reads(expr)
        self._record_donations(expr)

    def visit_simple(self, stmt: ast.stmt) -> None:
        self._check_reads(stmt)
        self._record_donations(stmt)
        if isinstance(stmt, ast.Assign):
            self._assign(stmt.targets, stmt.value)
        elif isinstance(stmt, ast.AnnAssign) and stmt.value is not None:
            self._assign([stmt.target], stmt.value)
        elif isinstance(stmt, ast.AugAssign):
            self._kill(stmt.target)

    def _assign(self, targets, value: ast.AST) -> None:
        spec = self._constructed_spec(value)
        device = self._is_device_value(value)
        for target in targets:
            self._kill(target)
            if isinstance(target, ast.Name):
                if spec is not None:
                    self.donating[target.id] = spec
                if device:
                    self.device.add(target.id)

    def _kill(self, target: ast.AST) -> None:
        if isinstance(target, ast.Name):
            self.donating.pop(target.id, None)
            self.device.discard(target.id)
            self.donated.pop(target.id, None)
        elif isinstance(target, (ast.Tuple, ast.List)):
            for elt in target.elts:
                self._kill(elt)
        elif isinstance(target, ast.Starred):
            self._kill(target.value)

    # -- loop back-edge: donation without re-staging ------------------------

    def on_for(self, stmt) -> None:
        self._kill(stmt.target)

    def begin_loop(self, stmt) -> None:
        assigned: Set[str] = set()
        for sub in ast.walk(stmt):
            if isinstance(sub, ast.Name) and isinstance(
                    sub.ctx, (ast.Store, ast.Del)):
                assigned.add(sub.id)
        self._loops.append((set(self.donated), assigned))

    def end_loop(self, stmt) -> None:
        pre_donated, loop_assigned = self._loops.pop()
        end = getattr(stmt, "end_lineno", stmt.lineno)
        for name, (line, via) in list(self.donated.items()):
            if name in pre_donated or not (stmt.lineno <= line <= end):
                continue
            if name in loop_assigned:
                continue
            del self.donated[name]
            if self.rule.suppressed(self.src, line, self.findings):
                continue
            self.findings.append(Finding(
                self.src.rel, line, self.rule.id,
                f"'{name}' is donated inside a loop without being re-staged "
                f"in the body (via {via}) — the next iteration would "
                "dispatch an already-donated buffer"))


@register
class UseAfterDonateRule(Rule):
    id = "use-after-donate"
    title = "donated device buffers are dead after the donating call"
    roots = ("video_features_tpu",)
    wrappers: Dict[str, _DonateSpec] = {}

    def prepare(self, root: str, sources, shared) -> None:
        # discover wiring wrappers: package functions that forward their own
        # parameter into a donating constructor with a literal donation
        self.wrappers = {}
        for rel, src in sorted(sources.items()):
            if getattr(src, "tree", None) is None:
                continue
            if not rel.startswith("video_features_tpu/"):
                continue
            if "donate_argnums" not in src.text:  # cheap pre-filter
                continue
            for node in ast.walk(src.tree):
                if not isinstance(node, (ast.FunctionDef,
                                         ast.AsyncFunctionDef)):
                    continue
                params = [a.arg for a in node.args.args]
                for call in ast.walk(node):
                    if not isinstance(call, ast.Call):
                        continue
                    base = _donating_base_call(call)
                    if base is None:
                        continue
                    argnums, fn_idx, via = base
                    if fn_idx >= len(call.args):
                        continue
                    fn_expr = call.args[fn_idx]
                    if (isinstance(fn_expr, ast.Name)
                            and fn_expr.id in params):
                        self.wrappers[node.name] = _DonateSpec(
                            argnums,
                            f"{node.name} → {via} [{rel}:{call.lineno}]")

    def check_file(self, src: SourceFile) -> Iterable[Finding]:
        findings: List[Finding] = []
        defs = [n for n in ast.walk(src.tree)
                if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))]
        nested = {sub for fn in defs for sub in ast.walk(fn)
                  if sub is not fn
                  and isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef))}
        for node in defs:
            if node in nested:
                continue
            _Scanner(self, src, findings).scan_block(node.body)
        self._check_pairs(src, findings)
        return sorted(set(findings),
                      key=lambda f: (f.path, f.line, f.message))

    # -- donation in/out pair check -----------------------------------------

    def _check_pairs(self, src: SourceFile,
                     findings: List[Finding]) -> None:
        """Every donating-constructor call whose wrapped fn resolves to a
        function in this module must return the donated parameter: XLA can
        only alias a donated input into a shape/dtype-identical output."""
        defs_by_name: Dict[str, List[ast.FunctionDef]] = {}
        for node in ast.walk(src.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                defs_by_name.setdefault(node.name, []).append(node)

        def resolve(fn_expr: ast.AST) -> Optional[ast.FunctionDef]:
            if isinstance(fn_expr, ast.Name):
                cands = defs_by_name.get(fn_expr.id, [])
                return cands[0] if len(cands) == 1 else None
            if isinstance(fn_expr, ast.Call):
                # one helper hop: paged_program(forward) → the nested def
                # its body returns
                name = dotted_name(fn_expr.func) or ""
                cands = defs_by_name.get(name.rsplit(".", 1)[-1], [])
                if len(cands) != 1:
                    return None
                for stmt in cands[0].body:
                    if (isinstance(stmt, ast.Return)
                            and isinstance(stmt.value, ast.Name)):
                        inner = [n for n in ast.walk(cands[0])
                                 if isinstance(n, ast.FunctionDef)
                                 and n.name == stmt.value.id]
                        return inner[0] if len(inner) == 1 else None
            return None

        for call in ast.walk(src.tree):
            if not isinstance(call, ast.Call):
                continue
            spec_via: Optional[str] = None
            argnums: Tuple[int, ...] = ()
            fn_expr: Optional[ast.AST] = None
            base = _donating_base_call(call)
            if base is not None:
                argnums, fn_idx, spec_via = base
                if fn_idx < len(call.args):
                    fn_expr = call.args[fn_idx]
            else:
                name = dotted_name(call.func) or ""
                wrapper = self.wrappers.get(name.rsplit(".", 1)[-1])
                if wrapper is not None and call.args:
                    argnums, spec_via = wrapper.argnums, wrapper.via
                    fn_expr = call.args[0]
            if fn_expr is None:
                continue
            target = resolve(fn_expr)
            if target is None:
                continue
            params = [a.arg for a in target.args.args]
            for argnum in argnums:
                if argnum >= len(params):
                    if self.suppressed(src, call.lineno, findings):
                        continue
                    findings.append(Finding(
                        src.rel, call.lineno, self.id,
                        f"donate_argnums={argnums} names no parameter of "
                        f"'{target.name}' (it takes {len(params)}) — via "
                        f"{spec_via}"))
                    continue
                param = params[argnum]
                for ret in self._returns(target):
                    value = ret.value
                    names = []
                    if isinstance(value, ast.Name):
                        names = [value.id]
                    elif isinstance(value, ast.Tuple):
                        names = [e.id for e in value.elts
                                 if isinstance(e, ast.Name)]
                    if param not in names:
                        if self.suppressed(src, ret.lineno, findings):
                            continue
                        findings.append(Finding(
                            src.rel, ret.lineno, self.id,
                            f"donated parameter '{param}' of "
                            f"'{target.name}' is not returned here — "
                            "donation needs a shape/dtype-identical in/out "
                            "pair (pass the buffer through verbatim, like "
                            f"the paged row table); via {spec_via}"))

    @staticmethod
    def _returns(fn: ast.FunctionDef) -> Iterable[ast.Return]:
        # returns of nested defs belong to those defs, not fn
        for stmt in fn.body:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.ClassDef)):
                continue
            for node in walk_no_defs(stmt):
                if isinstance(node, ast.Return) and node.value is not None:
                    yield node
