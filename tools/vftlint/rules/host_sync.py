"""host-sync: device arrays may only reach the host through accounted sites.

Every ``np.asarray``/``float``/``int``/``.item()`` on a device array blocks
the host on the device stream — 100-200 ms per sync on a tunneled TPU, and
invisible to profiling because the cost books to whatever Python line happened
to touch the array. The extractor contract routes all materialization through
``Extractor._wait`` (``utils.metrics`` ``device_wait``-accounted) so the
per-video stage report stays honest and stray syncs can't creep into step
loops.

Two analyses:

1. **Extractor taint scan** (``extractors/*.py``): a line-order dataflow pass
   marks values produced by device-step calls (``self._*step*``,
   ``_device_call``), ``runner.put``/``put_replicated``, ``jnp.*``,
   ``prefetch_to_device``, and device-pinned ``*params`` attributes; flags
   host-materializing sinks on tainted values outside ``_wait``.
2. **Traced-body scan** (whole package): host-materializing calls inside
   jit/shard_map-traced functions are flagged unconditionally — they force a
   concretization mid-trace.

Single pass, no back-edge fixpoint: a taint born at the bottom of a loop body
is not seen at its top. Good enough — step results are consumed below their
dispatch everywhere in this tree, and the fixture tests pin the contract.

Suppress a deliberate sync with ``# host-sync: <reason>`` (e.g. the flow
precompile warmup thread, which blocks off the critical path by design).
"""

from __future__ import annotations

import ast
import re
from typing import Iterable, List, Set

from ..core import Finding, Rule, SourceFile, register
from ..dataflow import LineOrderScanner
from ..tracing import dotted_name, walk_body

# attribute names whose CALL yields a device value
_STEP_ATTR = re.compile(r"(^|_)step(_|$)|(^|_)device_call$")
# attribute READS that are device-pinned values (MeshRunner.put_replicated)
_PARAMS_ATTR = re.compile(r"params$")
# methods that ARE the accounted materialization site
_ACCOUNTED_METHODS = {"_wait"}

_SINK_CALLS = {
    "np.asarray", "np.array", "numpy.asarray", "numpy.array",
    "jax.device_get", "jax.block_until_ready",
}
_SINK_BUILTINS = {"float", "int"}
_SINK_METHODS = {"item", "block_until_ready"}


def _is_device_callable_expr(node: ast.AST) -> bool:
    if isinstance(node, ast.Attribute):
        return bool(_STEP_ATTR.search(node.attr))
    if isinstance(node, ast.IfExp):
        return (_is_device_callable_expr(node.body)
                or _is_device_callable_expr(node.orelse))
    return False


class _TaintScanner(LineOrderScanner):
    """One function body's line-order taint pass.

    The statement walk (branch-union ``if``, closure-seeded nested defs,
    compound heads visited before their blocks) lives in
    :class:`~tools.vftlint.dataflow.LineOrderScanner`; this class supplies
    the host-sync state — tainted names and device-callable names — and the
    sink checks."""

    def __init__(self, rule: "HostSyncRule", src: SourceFile,
                 findings: List[Finding]):
        self.rule = rule
        self.src = src
        self.findings = findings
        self.tainted: Set[str] = set()
        self.device_callables: Set[str] = set()

    # -- LineOrderScanner state protocol ------------------------------------

    def snapshot(self):
        return (set(self.tainted), set(self.device_callables))

    def restore(self, token) -> None:
        self.tainted, self.device_callables = set(token[0]), set(token[1])

    def merged(self, tokens):
        out_t: Set[str] = set()
        out_c: Set[str] = set()
        for t, c in tokens:
            out_t |= t
            out_c |= c
        return (out_t, out_c)

    # -- expression taint ---------------------------------------------------

    def is_tainted(self, node: ast.AST) -> bool:
        if isinstance(node, ast.Name):
            return node.id in self.tainted
        if isinstance(node, ast.Attribute):
            return bool(_PARAMS_ATTR.search(node.attr))
        if isinstance(node, ast.Subscript):
            return self.is_tainted(node.value)
        if isinstance(node, ast.Starred):
            return self.is_tainted(node.value)
        if isinstance(node, (ast.Tuple, ast.List)):
            return any(self.is_tainted(e) for e in node.elts)
        if isinstance(node, ast.BinOp):
            return self.is_tainted(node.left) or self.is_tainted(node.right)
        if isinstance(node, ast.UnaryOp):
            return self.is_tainted(node.operand)
        if isinstance(node, ast.IfExp):
            return self.is_tainted(node.body) or self.is_tainted(node.orelse)
        if isinstance(node, ast.Call):
            return self.call_returns_device(node)
        return False

    def call_returns_device(self, call: ast.Call) -> bool:
        name = dotted_name(call.func) or ""
        last = name.rsplit(".", 1)[-1]
        if isinstance(call.func, ast.Attribute):
            if _STEP_ATTR.search(call.func.attr):
                return True
            if call.func.attr in ("put", "put_replicated"):
                return True
            # method on a device value stays on device (.astype, .reshape…)
            if (call.func.attr not in _SINK_METHODS
                    and self.is_tainted(call.func.value)):
                return True
        if name.startswith(("jnp.", "jax.numpy.")):
            return True
        if last == "prefetch_to_device":
            return True
        if isinstance(call.func, ast.Name):
            return call.func.id in self.device_callables
        return False

    # -- sink detection -----------------------------------------------------

    def check_sinks(self, root: ast.AST) -> None:
        """Flag sinks in ``root`` — a simple statement or a bare expression.
        Compound statements must NOT be passed whole: their blocks are
        scanned by :meth:`scan_block` after the state updates that scope
        them, so walking them here would re-check inner sinks against the
        stale pre-block taint (e.g. a value re-assigned from ``_wait``
        inside a branch would still read as tainted)."""
        for node in ast.walk(root):
            if not isinstance(node, ast.Call):
                continue
            name = dotted_name(node.func) or ""
            hit = None
            if name in _SINK_CALLS or name in _SINK_BUILTINS:
                if any(self.is_tainted(a) for a in node.args):
                    hit = f"{name}()"
            elif (isinstance(node.func, ast.Attribute)
                    and node.func.attr in _SINK_METHODS
                    and self.is_tainted(node.func.value)):
                hit = f".{node.func.attr}()"
            if hit is None:
                continue
            if self.rule.suppressed(self.src, node.lineno, self.findings):
                continue
            self.findings.append(Finding(
                self.src.rel, node.lineno, self.rule.id,
                f"{hit} on a device array outside the accounted sites — "
                "route host materialization through self._wait() "
                "(metrics 'device_wait') instead"))

    # -- statement-walk hooks (structure lives in LineOrderScanner) ---------

    def visit_expr(self, expr: ast.AST) -> None:
        self.check_sinks(expr)

    def on_for(self, stmt) -> None:
        if self.is_tainted(stmt.iter):
            self._mark(stmt.target, True)

    def visit_simple(self, stmt: ast.stmt) -> None:
        # simple statement: no nested blocks, safe to walk whole
        self.check_sinks(stmt)
        if isinstance(stmt, ast.Assign):
            self._assign(stmt.targets, stmt.value)
        elif isinstance(stmt, ast.AnnAssign) and stmt.value is not None:
            self._assign([stmt.target], stmt.value)
        elif isinstance(stmt, ast.AugAssign):
            if self.is_tainted(stmt.value):
                self._mark(stmt.target, True)

    def _assign(self, targets, value) -> None:
        tainted = self.is_tainted(value)
        callable_ = _is_device_callable_expr(value)
        for target in targets:
            self._mark(target, tainted)
            if isinstance(target, ast.Name):
                if callable_:
                    self.device_callables.add(target.id)
                else:
                    self.device_callables.discard(target.id)

    def _mark(self, target: ast.AST, tainted: bool) -> None:
        if isinstance(target, ast.Name):
            if tainted:
                self.tainted.add(target.id)
            else:
                self.tainted.discard(target.id)
        elif isinstance(target, (ast.Tuple, ast.List)):
            for elt in target.elts:
                self._mark(elt, tainted)
        elif isinstance(target, ast.Starred):
            self._mark(target.value, tainted)


@register
class HostSyncRule(Rule):
    id = "host-sync"
    title = "device→host materialization only via accounted sites"
    roots = ("video_features_tpu",)

    def check_file(self, src: SourceFile) -> Iterable[Finding]:
        findings: List[Finding] = []
        if src.rel.startswith("video_features_tpu/extractors/"):
            defs = [n for n in ast.walk(src.tree)
                    if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))]
            # nested defs are scanned by their parent with closure state
            nested = {sub for fn in defs for sub in ast.walk(fn)
                      if sub is not fn
                      and isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef))}
            for node in defs:
                if node.name in _ACCOUNTED_METHODS or node in nested:
                    continue
                scanner = _TaintScanner(self, src, findings)
                scanner.scan_block(node.body)
        # traced bodies anywhere: a host-materializing call mid-trace forces
        # concretization (or burns a constant) regardless of dataflow
        for fn in src.traced():  # memoized: shared with jit-purity
            for node in walk_body(fn):
                if not isinstance(node, ast.Call):
                    continue
                name = dotted_name(node.func) or ""
                hit = None
                if name in _SINK_CALLS:
                    hit = f"{name}()"
                elif (isinstance(node.func, ast.Attribute)
                        and node.func.attr in _SINK_METHODS):
                    hit = f".{node.func.attr}()"
                if hit is None:
                    continue
                if self.suppressed(src, node.lineno, findings):
                    continue
                findings.append(Finding(
                    src.rel, node.lineno, self.id,
                    f"{hit} inside traced function '{fn.name}' forces a "
                    "mid-trace host sync — keep the traced body on device"))
        # the two scans can overlap on extractor step bodies
        return sorted(set(findings),
                      key=lambda f: (f.path, f.line, f.message))
