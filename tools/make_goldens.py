"""Freeze golden-feature fixtures: torch-mirror outputs on the sample videos.

Why frozen files instead of the live mirror oracle (VERDICT r2, Missing #1):
every parity test recomputes the torch mirror at test time, so a regression
introduced SYMMETRICALLY — an edit to a shared constant, or an environment
torch upgrade shifting mirror numerics — moves both sides at once and no test
fails. These fixtures pin the expected feature values at generation time;
``tests/test_frozen_goldens.py`` then runs the PRODUCTION ``extract()`` (real
decode → host transforms → device step) against the stored arrays.

Weights are the deterministic torch-seeded state dicts from
``tools/torch_mirrors`` (the pretrained blobs are not available in this
environment — SURVEY.md §2.1 #25); each fixture records a weight fingerprint so
a torch-RNG drift is reported as "stale golden", not a false code regression.

Determinism pins baked into the fixtures (and asserted by the test):
- ``use_ffmpeg="never"``: fps resampling via the native sampler, so hosts with
  and without ffmpeg decode identical frames;
- fp32 everywhere, single device.

Regenerate (only after an intentional behavior change, on CPU):
    JAX_PLATFORMS=cpu python tools/make_goldens.py

Storage: flow fields are strided (pairs + spatial) to keep each ``.npz`` small;
the strides are recorded in the file and applied to the live output before
comparison.
"""

from __future__ import annotations

import os
import sys
import wave

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

import torch  # noqa: E402
import torch.nn.functional as F  # noqa: E402

import torch_mirrors as tm  # noqa: E402

from video_features_tpu.io.video import decode_all, open_video  # noqa: E402
from video_features_tpu.ops.image import np_center_crop_hwc, pil_edge_resize  # noqa: E402
from video_features_tpu.utils.windows import form_slices  # noqa: E402

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
GOLDEN_DIR = os.path.join(REPO, "tests", "goldens")
SAMPLES = {
    "v1": os.path.join(REPO, "sample", "v_GGSY1Qvo990.mp4"),
    "v2": os.path.join(REPO, "sample", "v_ZNVhz7ctTq0.mp4"),
}

# model → (state-dict builder, seed); shared by the generator and the test
SEEDS = {
    "resnet50": 4,
    "i3d_rgb": 6,
    "i3d_flow": 7,
    "pwc": 0,
    "raft": 0,
    "r21d": 0,
    "vggish": 3,
}


def state_dict_for(model: str):
    if model == "resnet50":
        return tm.random_init_(tm.ResNet50(), seed=SEEDS[model]).state_dict()
    if model in ("i3d_rgb", "i3d_flow"):
        return tm.i3d_random_state_dict(model.split("_")[1], seed=SEEDS[model])
    if model == "pwc":
        return tm.pwc_random_state_dict(seed=SEEDS[model])
    if model == "raft":
        sd = tm.raft_random_state_dict(seed=SEEDS[model])
        # Damp the per-iteration flow delta: with undamped random weights the
        # 20-iteration refinement is NOT contractive (|flow| reaches ~400 px)
        # and last-ulp jax-vs-torch differences chaotically divide the field —
        # the fixture would pin noise. The trained checkpoint is contractive;
        # a small flow head restores that property for the random fixture.
        sd["update_block.flow_head.conv2.weight"] = (
            sd["update_block.flow_head.conv2.weight"] * 0.02)
        sd["update_block.flow_head.conv2.bias"] = (
            sd["update_block.flow_head.conv2.bias"] * 0.02)
        return sd
    if model == "r21d":
        return tm.r21d_random_state_dict(seed=SEEDS[model])
    raise KeyError(model)


def fingerprint(sd: dict) -> np.ndarray:
    """Order-independent weight digest: (sum, abs-sum, count) over all leaves."""
    tot = np.float64(0)
    atot = np.float64(0)
    n = 0
    for v in sd.values():
        a = v.detach().cpu().numpy().astype(np.float64)
        tot += a.sum()
        atot += np.abs(a).sum()
        n += a.size
    return np.array([tot, atot, n], np.float64)


def synth_wav(path: str) -> None:
    """Deterministic 3 s two-tone test signal (the sample mp4s need ffmpeg for
    audio extraction, which this environment lacks)."""
    t = np.arange(16000 * 3) / 16000.0
    sig = 0.4 * np.sin(2 * np.pi * 440 * t) + 0.2 * np.sin(2 * np.pi * 1330 * t)
    pcm = (sig * 32767).astype(np.int16)
    with wave.open(path, "wb") as w:
        w.setnchannels(1)
        w.setsampwidth(2)
        w.setframerate(16000)
        w.writeframes(pcm.tobytes())


def decode(path, fps=None, transform=None):
    _, frames_iter = open_video(path, extraction_fps=fps, use_ffmpeg="never",
                                transform=transform)
    return np.stack([rgb for rgb, _ in frames_iter])


# --- mirror pipelines (host logic mirrors the extractors; nets from torch) ---


def golden_resnet50(video: str) -> dict:
    sd = state_dict_for("resnet50")
    model = tm.ResNet50()
    model.load_state_dict(sd)
    model.eval()  # running-stat BatchNorm — train mode would use batch stats
    frames = decode(video, fps=8, transform=lambda rgb: np_center_crop_hwc(
        pil_edge_resize(rgb, 256), 224, 224))
    x = frames.astype(np.float32) / 255.0
    from video_features_tpu.models.resnet import IMAGENET_MEAN, IMAGENET_STD

    x = (x - np.asarray(IMAGENET_MEAN)) / np.asarray(IMAGENET_STD)
    with torch.no_grad():
        feats = model(torch.from_numpy(x.transpose(0, 3, 1, 2).astype(np.float32)),
                      features=True).numpy()
    return {"features": feats[::4], "stride0": 4, "fp": fingerprint(sd),
            "cfg_extraction_fps": 8}


def golden_r21d(video: str) -> dict:
    sd = state_dict_for("r21d")
    _, frames, _ = decode_all(video, extraction_fps=None)
    slices = form_slices(frames.shape[0], 16, 16)
    feats = []
    with torch.no_grad():
        for s, e in slices:
            clip = torch.from_numpy(frames[s:e].astype(np.float32) / 255.0)
            clip = clip.permute(0, 3, 1, 2)  # (T, C, H, W)
            clip = F.interpolate(clip, size=(128, 171), mode="bilinear",
                                 align_corners=False)
            mean = torch.tensor([0.43216, 0.394666, 0.37645]).view(3, 1, 1)
            std = torch.tensor([0.22803, 0.22145, 0.216989]).view(3, 1, 1)
            clip = (clip - mean) / std
            # torchvision CenterCrop rounds half offsets (rgb_transforms.py:14-20):
            # (171-112)/2 = 29.5 → 30, NOT floor 29
            top = int(round((128 - 112) / 2.0))
            left = int(round((171 - 112) / 2.0))
            clip = clip[:, :, top : top + 112, left : left + 112]
            x = clip.permute(1, 0, 2, 3)[None]  # (1, C, T, H, W)
            feats.append(tm.r21d_forward(sd, x, features=True).numpy()[0])
    return {"features": np.stack(feats), "fp": fingerprint(sd)}


def golden_flow(video: str, kind: str) -> dict:
    """RAFT / PWC dense flow, mirroring ExtractFlow's batching + pad logic."""
    sd = state_dict_for(kind)
    frames = decode(video, fps=2,
                    transform=lambda rgb: pil_edge_resize(rgb, 128)).astype(np.float32)
    if kind == "raft":
        from video_features_tpu.models.raft import pad_to_multiple

        padded, pads = pad_to_multiple(frames, 8)
    else:
        padded, pads = frames, (0, 0, 0, 0)
    x = torch.from_numpy(padded.transpose(0, 3, 1, 2))
    flows = []
    with torch.no_grad():
        for i in range(len(frames) - 1):
            if kind == "raft":
                fl = tm.raft_torch_forward(sd, x[i : i + 1], x[i + 1 : i + 2])
            else:
                fl = tm.pwc_torch_forward(sd, x[i : i + 1], x[i + 1 : i + 2])
            flows.append(fl.numpy()[0])
    flow = np.stack(flows)  # (P, 2, Hp, Wp)
    top, bottom, left, right = pads
    h, w = flow.shape[-2:]
    flow = flow[..., top : h - bottom, left : w - right]
    return {"features": flow[::6, :, ::4, ::4], "stride0": 6, "stride_hw": 4,
            "fp": fingerprint(sd), "cfg_extraction_fps": 2, "cfg_side_size": 128}


def golden_i3d(video: str) -> dict:
    """Two-stream I3D with the PWC flow sandwich (stack 16 / step 16, fps 4)."""
    sd_rgb = state_dict_for("i3d_rgb")
    sd_flow = state_dict_for("i3d_flow")
    sd_pwc = state_dict_for("pwc")
    frames = decode(video, fps=4, transform=lambda rgb: pil_edge_resize(rgb, 256))
    stack_size = step_size = 16
    h, w = frames.shape[1:3]
    fh, fw = (h - 224) // 2, (w - 224) // 2
    rgb_feats, flow_feats = [], []
    start = 0
    with torch.no_grad():
        while start + stack_size + 1 <= len(frames):
            stack = frames[start : start + stack_size + 1].astype(np.float32)
            start += step_size
            # rgb stream: drop last frame, crop 224, scale [-1, 1]
            crop = stack[:-1, fh : fh + 224, fw : fw + 224, :]
            xr = 2.0 * crop / 255.0 - 1.0
            xr = torch.from_numpy(xr.transpose(3, 0, 1, 2)[None])
            rgb_feats.append(tm.i3d_forward(sd_rgb, xr, features=True).numpy()[0])
            # flow stream: PWC on the 256-edge frames, crop AFTER (reference
            # transform order), clamp ±20 → uint8 quantize → [-1, 1]
            xt = torch.from_numpy(stack.transpose(0, 3, 1, 2))
            fl = []
            for i in range(stack_size):
                fl.append(tm.pwc_torch_forward(sd_pwc, xt[i : i + 1],
                                               xt[i + 1 : i + 2]).numpy()[0])
            fl = np.stack(fl)  # (S, 2, H, W)
            fl = fl[:, :, fh : fh + 224, fw : fw + 224]
            q = np.round(128.0 + 255.0 / 40.0 * np.clip(fl, -20, 20))
            xf = (2.0 * q / 255.0 - 1.0).astype(np.float32)
            xf = torch.from_numpy(xf.transpose(1, 0, 2, 3)[None])
            flow_feats.append(tm.i3d_forward(sd_flow, xf, features=True).numpy()[0])
    return {"rgb": np.stack(rgb_feats), "flow": np.stack(flow_feats),
            "fp_rgb": fingerprint(sd_rgb), "fp_flow": fingerprint(sd_flow),
            "fp_pwc": fingerprint(sd_pwc), "cfg_extraction_fps": 4}


def golden_vggish(wav_path: str) -> dict:
    """VGGish on the synthetic wav through the production DSP + torch net mirror
    (the torch mirror here matches tests/test_vggish.py::test_network_parity_vs_torch)."""
    from video_features_tpu.audio.melspec import wav_to_examples
    from video_features_tpu.models.vggish import vggish_init_params

    params = vggish_init_params(seed=SEEDS["vggish"])
    examples = wav_to_examples(wav_path)
    t = torch.from_numpy(examples)[:, None]
    with torch.no_grad():
        for name in ("conv1", "conv2", "conv3_1", "conv3_2", "conv4_1", "conv4_2"):
            wk = torch.from_numpy(np.transpose(params[name]["kernel"], (3, 2, 0, 1)))
            b = torch.from_numpy(params[name]["bias"])
            t = F.relu(F.conv2d(t, wk, b, 1, 1))
            if name in ("conv1", "conv2", "conv3_2", "conv4_2"):
                t = F.max_pool2d(t, 2, 2)
        t = t.permute(0, 2, 3, 1).reshape(len(examples), -1)
        for name in ("fc1_1", "fc1_2", "fc2"):
            wk = torch.from_numpy(params[name]["kernel"])
            b = torch.from_numpy(params[name]["bias"])
            t = F.relu(t @ wk + b)
    flat_sum = np.float64(sum(float(leaf.sum()) for mod in params.values()
                              for leaf in mod.values()))
    flat_abs = np.float64(sum(float(np.abs(leaf).sum()) for mod in params.values()
                              for leaf in mod.values()))
    n = sum(leaf.size for mod in params.values() for leaf in mod.values())
    return {"features": t.numpy(), "fp": np.array([flat_sum, flat_abs, n], np.float64)}


def main() -> None:
    os.makedirs(GOLDEN_DIR, exist_ok=True)
    wav = os.path.join(GOLDEN_DIR, "tone.wav")
    synth_wav(wav)

    jobs = []
    for vid, path in SAMPLES.items():
        jobs += [
            (f"resnet50_{vid}", lambda p=path: golden_resnet50(p)),
            (f"r21d_{vid}", lambda p=path: golden_r21d(p)),
            (f"raft_{vid}", lambda p=path: golden_flow(p, "raft")),
            (f"pwc_{vid}", lambda p=path: golden_flow(p, "pwc")),
            (f"i3d_{vid}", lambda p=path: golden_i3d(p)),
        ]
    jobs.append(("vggish_tone", lambda: golden_vggish(wav)))

    for name, fn in jobs:
        out = os.path.join(GOLDEN_DIR, f"{name}.npz")
        print(f"generating {name} ...", flush=True)
        arrays = fn()
        np.savez_compressed(out, **arrays)
        sz = os.path.getsize(out) // 1024
        print(f"  wrote {out} ({sz} KiB): "
              f"{ {k: getattr(v, 'shape', v) for k, v in arrays.items()} }", flush=True)


if __name__ == "__main__":
    main()
