#!/usr/bin/env python
"""Service smoke: spool-directory ingest → drain → manifest/output parity.

The CI-runnable end-to-end check for the always-on daemon (docs/serving.md),
driving the REAL CLI surface as an operator would — no test harness imports:

1. two per-tenant batch CLI runs produce the reference outputs;
2. a daemon subprocess (``--serve``, spool ingest, real signals, a
   ``--cache_dir`` feature cache) serves the same videos as two tenant
   requests dropped into the spool;
3. a RESUBMIT of alice's videos must be served entirely from the feature
   cache (``cache_hits`` in its result record, hits in the socket ``stats``
   op — docs/caching.md);
3b. telemetry (docs/observability.md): the daemon runs with
   ``--telemetry_dir``; the script asserts the versioned ``stats`` payload
   (``"schema": 1`` + per-tenant latency summaries), hits ``healthz`` and
   ``metrics`` (Prometheus text), runs one ``profile start/stop`` cycle
   against the live daemon, and — after the drain — exports the journal
   with ``python -m video_features_tpu.obs.export`` and validates the
   Chrome trace parses as JSON with a complete span chain per request;
4. the daemon co-loads a second model (``--serve_models r21d_rgb``,
   docs/serving.md): a mixed-traffic step submits carol's request with
   ``"feature_type": "r21d_rgb"`` to the SAME daemon — carol's two videos
   carry DIFFERENT native geometries, so the daemon serves mixed-geometry
   traffic through the default ragged paged dispatch (docs/performance.md)
   — and asserts byte-parity against a single-model r21d batch run,
   per-model sections plus the paged counters (``pages_dispatched``,
   ``max_in_flight`` ≥ 2, ``page_occupancy``) in the socket ``stats`` op,
   the ``vft_page_occupancy`` gauge in the ``metrics`` op, and a clean
   ``rejected`` record for a request naming an unloaded model;
5. SIGTERM drains it, and the script asserts exit code 0, ``done`` result
   records for every request, complete per-model done-manifests, and
   byte-identical ``.npy`` outputs against the batch runs;
6. a second, dedicated daemon runs with ``--device_preproc`` (the raw-pixels
   wire — docs/performance.md) and serves one mixed-geometry request: the
   outputs must track a ``--device_preproc`` batch run to float32 ulp level
   (the daemon's paged dispatch runs the fused resize at page shape, so
   byte-parity is not the contract there), and the ``stats`` op must report
   the decode/transfer stage split — the operator meter showing the decode
   pool shed the per-frame PIL work.

Runs on CPU with deterministic random weights::

    JAX_PLATFORMS=cpu VFT_ALLOW_RANDOM_WEIGHTS=1 python tools/service_smoke.py

Exit code 0 = pass; any assertion or timeout raises.
"""

import glob
import json
import os
import signal
import socket
import subprocess
import sys
import tempfile
import time

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
TIMEOUT = float(os.environ.get("VFT_SMOKE_TIMEOUT", "600"))


def write_video(path, frames, size=(32, 24)):
    import cv2

    w = cv2.VideoWriter(path, cv2.VideoWriter_fourcc(*"mp4v"), 10.0, size)
    rng = np.random.default_rng(frames)
    for _ in range(frames):
        w.write(rng.integers(0, 256, (size[1], size[0], 3), dtype=np.uint8))
    w.release()
    return path


def cli(out_dir, *extra, feature="resnet50"):
    return [sys.executable, os.path.join(REPO, "main.py"),
            "--feature_type", feature, "--on_extraction", "save_numpy",
            "--batch_size", "4", "--output_path", out_dir, *extra]


def outputs(out_dir, feature="resnet50"):
    return {os.path.basename(p): np.load(p)
            for p in glob.glob(os.path.join(out_dir, feature, "*.npy"))}


def sock_op(sock_path, op):
    """One line-JSON round-trip on the daemon's control socket (stdlib only,
    like the rest of this operator-shaped script)."""
    with socket.socket(socket.AF_UNIX, socket.SOCK_STREAM) as s:
        s.settimeout(10.0)
        s.connect(sock_path)
        s.sendall(json.dumps(op).encode() + b"\n")
        buf = b""
        while b"\n" not in buf:
            chunk = s.recv(65536)
            if not chunk:
                break
            buf += chunk
    return json.loads(buf.split(b"\n", 1)[0].decode())


def drop_request(spool, request_id, payload):
    tmp = os.path.join(spool, f".{request_id}.tmp")
    with open(tmp, "w") as f:
        json.dump(payload, f)
    os.replace(tmp, os.path.join(spool, f"{request_id}.json"))


def await_results(daemon, paths, deadline):
    while time.time() < deadline:
        if daemon.poll() is not None:
            raise AssertionError(
                f"daemon exited early with {daemon.returncode}")
        if all(os.path.exists(p) for p in paths):
            return
        time.sleep(0.2)
    raise AssertionError("timed out waiting for result records")


def main() -> int:
    env = {**os.environ, "JAX_PLATFORMS": "cpu",
           "VFT_ALLOW_RANDOM_WEIGHTS": "1"}
    root = tempfile.mkdtemp(prefix="vft_service_smoke_")
    videos = {"alice": [write_video(os.path.join(root, f"a{i}.mp4"), n)
                        for i, n in enumerate((3, 6))],
              "bob": [write_video(os.path.join(root, f"b{i}.mp4"), n)
                      for i, n in enumerate((5, 2))]}
    # carol's videos go to the co-loaded r21d_rgb model (>=16 frames: one
    # full reference stack each) with DIFFERENT native geometries — r21d
    # keys paged bucket families per decoded shape, so this is the
    # mixed-geometry paged-serving traffic the stats assertions below pin
    r21d_videos = [write_video(os.path.join(root, "c0.mp4"), 16),
                   write_video(os.path.join(root, "c1.mp4"), 18,
                               size=(48, 32))]

    print("[smoke] per-tenant batch reference runs")
    for tenant, vids in videos.items():
        subprocess.run(cli(os.path.join(root, f"batch_{tenant}"),
                           "--video_paths", *vids),
                       env=env, check=True, timeout=TIMEOUT)
    print("[smoke] single-model r21d_rgb batch reference run")
    subprocess.run(cli(os.path.join(root, "batch_r21d"),
                       "--video_paths", *r21d_videos, feature="r21d_rgb"),
                   env=env, check=True, timeout=TIMEOUT)

    spool = os.path.join(root, "spool")
    os.makedirs(spool)
    serve_out = os.path.join(root, "serve")
    print("[smoke] starting the daemon (co-resident models: resnet50 + "
          "r21d_rgb)")
    telemetry_dir = os.path.join(root, "telemetry")
    daemon = subprocess.Popen(
        cli(serve_out, "--serve", "--spool_dir", spool,
            "--idle_flush_sec", "0.05", "--spool_poll_sec", "0.05",
            "--serve_models", "r21d_rgb",
            "--cache_dir", os.path.join(root, "cache"),
            "--telemetry_dir", telemetry_dir),
        env=env)
    try:
        for tenant, vids in videos.items():
            drop_request(spool, f"req_{tenant}",
                         {"tenant": tenant, "videos": vids})

        results = {t: os.path.join(spool, "results", f"req_{t}.result.json")
                   for t in videos}
        await_results(daemon, results.values(), time.time() + TIMEOUT)

        for tenant, path in results.items():
            with open(path) as f:
                record = json.load(f)
            assert record["state"] == "done", (tenant, record)
            assert sorted(record["done"]) == sorted(
                os.path.abspath(v) for v in videos[tenant]), record
            print(f"[smoke] request {tenant}: done "
                  f"({len(record['done'])} videos)")

        # resubmit alice's videos: the feature cache must serve every one
        # (zero device steps) and say so in the result record + stats op
        print("[smoke] resubmitting alice's videos (expect cache hits)")
        drop_request(spool, "req_alice2",
                     {"tenant": "alice", "videos": videos["alice"]})
        resubmit = os.path.join(spool, "results", "req_alice2.result.json")
        await_results(daemon, [resubmit], time.time() + TIMEOUT)
        with open(resubmit) as f:
            record = json.load(f)
        assert record["state"] == "done", record
        assert record["cache_hits"] == len(videos["alice"]), record
        stats = sock_op(os.path.join(spool, "control.sock"), {"op": "stats"})
        # versioned payload: external scrapers pin the schema key and treat
        # a bump as a breaking change (docs/serving.md documents the tree)
        assert stats["schema"] == 1, stats.get("schema")
        assert stats["cache"]["hits"] >= len(videos["alice"]), stats["cache"]
        assert stats["cache"]["hit_rate"] > 0, stats["cache"]
        print(f"[smoke] resubmit served from cache "
              f"({record['cache_hits']} hits; cumulative hit rate "
              f"{stats['cache']['hit_rate']:.0%})")

        # telemetry ops: healthz liveness, Prometheus metrics, and one
        # profile start/stop cycle against the LIVE daemon
        sock = os.path.join(spool, "control.sock")
        health = sock_op(sock, {"op": "healthz"})
        assert health["ok"] and health["stale"] is False, health
        assert health["uptime_sec"] > 0, health
        metrics = sock_op(sock, {"op": "metrics"})
        assert metrics["ok"] and metrics["schema"] == 1, metrics.get("ok")
        assert "vft_e2e_latency_seconds_bucket" in metrics["prometheus"], \
            metrics["prometheus"][:400]
        latency = {s["labels"]["tenant"]: s
                   for s in stats["latency"]["e2e"]}
        assert {"alice", "bob"} <= set(latency), stats["latency"]
        assert all(s["p50"] <= s["p99"] for s in latency.values()), latency
        print(f"[smoke] healthz ok (last step {health['last_step_age_sec']}s"
              f" ago); e2e p99: "
              + ", ".join(f"{t}={s['p99']}s" for t, s in latency.items()))
        prof = sock_op(sock, {"op": "profile", "action": "start"})
        assert prof["ok"], prof
        prof2 = sock_op(sock, {"op": "profile", "action": "stop"})
        assert prof2["ok"], prof2
        print(f"[smoke] profile cycle ok → {prof2['trace_dir']}")

        # two-model mixed traffic: carol's r21d_rgb request rides the SAME
        # daemon/mesh as the resnet50 tenants; byte parity vs the
        # single-model batch run is asserted after the drain below
        print("[smoke] submitting carol's r21d_rgb request (co-resident "
              "model)")
        drop_request(spool, "req_carol",
                     {"tenant": "carol", "videos": r21d_videos,
                      "feature_type": "r21d_rgb"})
        carol = os.path.join(spool, "results", "req_carol.result.json")
        await_results(daemon, [carol], time.time() + TIMEOUT)
        with open(carol) as f:
            record = json.load(f)
        assert record["state"] == "done", record
        assert record["feature_type"] == "r21d_rgb", record

        # a request naming an UNLOADED model must produce a clean rejection
        # record, not a daemon crash or a silent terminal failure
        print("[smoke] submitting a request for an unloaded model "
              "(expect rejection record)")
        drop_request(spool, "req_unknown",
                     {"tenant": "carol", "videos": videos["alice"],
                      "feature_type": "vggish"})
        unknown = os.path.join(spool, "results", "req_unknown.result.json")
        await_results(daemon, [unknown], time.time() + TIMEOUT)
        with open(unknown) as f:
            record = json.load(f)
        assert record["state"] == "rejected", record
        assert "not loaded" in record["reason"], record
        assert os.path.exists(os.path.join(spool,
                                           "req_unknown.json.rejected"))

        stats = sock_op(os.path.join(spool, "control.sock"), {"op": "stats"})
        assert stats["serving_models"] == ["resnet50", "r21d_rgb"], stats
        assert set(stats["models"]) == {"resnet50", "r21d_rgb"}, \
            stats["models"]
        for model, m in stats["models"].items():
            assert m["videos_ok"] > 0 and m["dispatched_slots"] > 0, \
                (model, m)
        print(f"[smoke] per-model stats: "
              + ", ".join(f"{m}: occupancy {s['occupancy']}"
                          for m, s in stats["models"].items()))

        # ragged paged dispatch (docs/performance.md): the default-on paged
        # mode must have carried the mixed-geometry traffic above — pages
        # dispatched, the double-buffered ring observed at depth >= 2, and
        # page_occupancy reported in the stats op + the metrics gauge
        packing = stats["packing"]
        assert packing["pages_dispatched"] > 0, packing
        assert packing["max_in_flight"] >= 2, packing
        assert packing["page_occupancy"] > 0, packing
        metrics = sock_op(os.path.join(spool, "control.sock"),
                          {"op": "metrics"})
        assert "vft_page_occupancy" in metrics["prometheus"], \
            metrics["prometheus"][:400]
        print(f"[smoke] paged dispatch: {packing['pages_dispatched']} pages, "
              f"max {packing['max_in_flight']} in flight, page occupancy "
              f"{packing['page_occupancy']}")

        print("[smoke] SIGTERM → graceful drain")
        daemon.send_signal(signal.SIGTERM)
        assert daemon.wait(timeout=TIMEOUT) == 0, daemon.returncode
    finally:
        if daemon.poll() is None:
            daemon.kill()
            daemon.wait()

    got = outputs(serve_out)
    want = {**outputs(os.path.join(root, "batch_alice")),
            **outputs(os.path.join(root, "batch_bob"))}
    assert set(got) == set(want), (sorted(got), sorted(want))
    for name in sorted(want):
        assert got[name].tobytes() == want[name].tobytes(), \
            f"{name}: daemon output differs from the batch run"
    # the co-resident model's outputs: byte-identical to the single-model
    # r21d batch run, in r21d's own output subtree
    got_r = outputs(serve_out, feature="r21d_rgb")
    want_r = outputs(os.path.join(root, "batch_r21d"), feature="r21d_rgb")
    assert set(got_r) == set(want_r) and got_r, (sorted(got_r),
                                                 sorted(want_r))
    for name in sorted(want_r):
        assert got_r[name].tobytes() == want_r[name].tobytes(), \
            f"{name}: two-model daemon r21d output differs from batch run"
    manifest = os.path.join(serve_out, "resnet50", ".done_manifest.jsonl")
    # cache-hit replays append their own records (resume-vs-cache layering
    # is deterministic), so count DISTINCT videos, not lines
    with open(manifest) as f:
        done = {json.loads(line)["video"] for line in f}
    assert len(done) == 4, f"done-manifest incomplete: {sorted(done)}"
    with open(os.path.join(serve_out, "r21d_rgb",
                           ".done_manifest.jsonl")) as f:
        done_r = {json.loads(line)["video"] for line in f}
    assert len(done_r) == len(r21d_videos), sorted(done_r)

    # telemetry journal → Chrome trace: the exported file must parse as
    # JSON and hold a COMPLETE request span (admitted→done, ph "X") for
    # every accepted request, plus ≥1 per-video span each
    print("[smoke] exporting the telemetry journal to a Chrome trace")
    journal = os.path.join(telemetry_dir, "events.jsonl")
    trace_path = os.path.join(root, "trace.json")
    subprocess.run([sys.executable, "-m", "video_features_tpu.obs.export",
                    journal, "-o", trace_path],
                   env=env, check=True, timeout=60, cwd=REPO)
    with open(trace_path) as f:
        trace = json.load(f)
    xs = [e for e in trace["traceEvents"] if e.get("ph") == "X"]
    req_spans = {e["args"].get("request") for e in xs
                 if e["name"] == "request"}
    accepted = {"req_alice", "req_bob", "req_alice2", "req_carol"}
    assert accepted <= req_spans, (sorted(req_spans), sorted(accepted))
    per_video = [e for e in xs if e["name"] in ("queue_wait", "process")]
    assert len(per_video) >= len(videos["alice"]) + len(videos["bob"]), \
        len(per_video)
    # the rejected request journaled its rejection, not a span
    instants = {(e.get("name"), e["args"].get("request"))
                for e in trace["traceEvents"] if e.get("ph") == "i"}
    assert ("request_rejected", "req_unknown") in instants
    print(f"[smoke] trace ok: {len(req_spans)} request spans, "
          f"{len(per_video)} per-video spans")

    # --device_preproc serving: a dedicated daemon with the raw-pixels wire
    # on, one mixed-geometry request, parity vs a --device_preproc batch run
    print("[smoke] --device_preproc daemon: one mixed-geometry request")
    dave_videos = [write_video(os.path.join(root, "d0.mp4"), 4),
                   write_video(os.path.join(root, "d1.mp4"), 5,
                               size=(48, 36))]
    subprocess.run(cli(os.path.join(root, "batch_dave"), "--device_preproc",
                       "--video_paths", *dave_videos),
                   env=env, check=True, timeout=TIMEOUT)
    spool2 = os.path.join(root, "spool_dp")
    os.makedirs(spool2)
    dp_out = os.path.join(root, "serve_dp")
    daemon2 = subprocess.Popen(
        cli(dp_out, "--serve", "--spool_dir", spool2, "--device_preproc",
            "--idle_flush_sec", "0.05", "--spool_poll_sec", "0.05"),
        env=env)
    try:
        drop_request(spool2, "req_dave",
                     {"tenant": "dave", "videos": dave_videos})
        dave = os.path.join(spool2, "results", "req_dave.result.json")
        await_results(daemon2, [dave], time.time() + TIMEOUT)
        with open(dave) as f:
            record = json.load(f)
        assert record["state"] == "done", record
        # the stats op's per-stage split: decode ran (and, with the raw
        # wire, did NO PIL work — the resize is fused into the step), and
        # the host→device transfer stage is accounted separately
        stats_dp = sock_op(os.path.join(spool2, "control.sock"),
                           {"op": "stats"})
        stages = stats_dp["stages"]
        assert stages.get("decode", 0) > 0, stages
        assert "transfer" in stages, stages
        assert stats_dp["transfer"]["bytes"] > 0, stats_dp["transfer"]
        print(f"[smoke] device_preproc stage split: decode "
              f"{stages['decode']}s, transfer {stages['transfer']}s "
              f"({stats_dp['transfer']['bytes']} B staged)")
        daemon2.send_signal(signal.SIGTERM)
        assert daemon2.wait(timeout=TIMEOUT) == 0, daemon2.returncode
    finally:
        if daemon2.poll() is None:
            daemon2.kill()
            daemon2.wait()
    got_dp = outputs(dp_out)
    want_dp = outputs(os.path.join(root, "batch_dave"))
    assert set(got_dp) == set(want_dp) and got_dp, (sorted(got_dp),
                                                    sorted(want_dp))
    for name in sorted(want_dp):
        w, g = want_dp[name], got_dp[name]
        assert w.shape == g.shape, name
        scale = max(1.0, float(np.abs(w).max()))
        assert np.abs(w - g).max() <= 1e-5 * scale, \
            f"{name}: device_preproc daemon output drifts past ulp level"
    print(f"[smoke] device_preproc outputs track the batch run "
          f"({len(want_dp)} files, ulp-level)")

    print(f"[smoke] PASS: {len(want)} + {len(want_r)} outputs "
          "byte-identical across two co-resident models, manifests intact, "
          "telemetry trace complete, device_preproc serving verified")
    return 0


if __name__ == "__main__":
    sys.exit(main())
