"""Segment-level I3D timing: which part of the headline model eats the step?

Times cumulative prefixes of the I3D spec walk (stem conv → pools/convs →
mixed_3 → mixed_4 → mixed_5 → head) as independent jitted programs on the live
backend; per-segment cost is the difference between adjacent prefixes. Same
unique-inputs methodology as tools/profile_raft.py (the axon tunnel memoizes
repeated calls).

Run: python tools/profile_i3d.py [clips] [stack] [dtype]
"""

from __future__ import annotations

import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
os.environ.setdefault("VFT_ALLOW_RANDOM_WEIGHTS", "1")

import flax.linen as nn  # noqa: E402
import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402

from _bench_util import enable_compilation_cache, time_fn  # noqa: E402

enable_compilation_cache()

from video_features_tpu.models.i3d import (  # noqa: E402
    I3D,
    I3D_STEM,
    Mixed,
    Unit3D,
    i3d_preprocess_rgb,
)
from video_features_tpu.models.layers import max_pool_tf_same  # noqa: E402


class I3DPrefix(nn.Module):
    """First ``n_ops`` entries of the I3D spec walk (random params per prefix)."""

    n_ops: int
    dtype: object = jnp.float32

    @nn.compact
    def __call__(self, x):
        x = x.astype(self.dtype)
        for op, name, *spec in I3D_STEM[: self.n_ops]:
            if op == "conv":
                feats, kernel, stride = spec
                x = Unit3D(feats, kernel, stride, dtype=self.dtype, name=name)(x)
            elif op == "pool":
                kernel, stride = spec
                x = max_pool_tf_same(x, kernel, stride)
            else:
                x = Mixed(spec[0], dtype=self.dtype, name=name)(x)
        return x


def main():
    clips = int(sys.argv[1]) if len(sys.argv) > 1 else 4
    stack = int(sys.argv[2]) if len(sys.argv) > 2 else 64
    dtype = {"float32": jnp.float32, "bfloat16": jnp.bfloat16}[
        sys.argv[3] if len(sys.argv) > 3 else "float32"
    ]
    rng = np.random.default_rng(0)
    print(f"backend={jax.default_backend()} clips={clips} stack={stack} "
          f"dtype={jnp.dtype(dtype).name}", flush=True)

    def frames():
        return jnp.asarray(
            rng.uniform(-1, 1, (clips, stack, 224, 224, 3)).astype(np.float32))

    segments = [
        ("stem_conv7", 1),
        ("convs+pools", 5),
        ("mixed_3b-3c", 7),
        ("mixed_4a-4f", 13),
        ("mixed_5a-5c", 16),
    ]
    prev_ms, prev_label = 0.0, "input"
    for label, n_ops in segments:
        model = I3DPrefix(n_ops=n_ops, dtype=dtype)
        params = model.init(jax.random.PRNGKey(0),
                            jnp.zeros((1, 16, 224, 224, 3)))["params"]
        params = jax.device_put(params)

        def fwd(p, x, model=model):
            return model.apply({"params": p}, x)

        step = jax.jit(fwd)
        ms = time_fn(f"thru_{label}", step, lambda: (params, frames()))
        print(f"{'Δ ' + label:>16}: {(ms - prev_ms) * 1e3:9.2f} ms", flush=True)
        prev_ms = ms

    # full model incl. head, and the real extractor preprocessing
    model = I3D(modality="rgb", dtype=dtype)
    params = jax.device_put(
        model.init(jax.random.PRNGKey(0), jnp.zeros((1, 16, 224, 224, 3)))["params"])

    def full(p, x):
        return model.apply({"params": p}, x, features=True)

    ms = time_fn("full+head", jax.jit(full), lambda: (params, frames()))
    print(f"{'Δ head':>16}: {(ms - prev_ms) * 1e3:9.2f} ms", flush=True)

    def full_pre(p, u8):
        return model.apply({"params": p}, i3d_preprocess_rgb(u8, dtype), features=True)

    def u8():
        return jnp.asarray(rng.integers(0, 256, (clips, stack, 224, 224, 3),
                                        dtype=np.uint8))

    time_fn("full+preproc", jax.jit(full_pre), lambda: (params, u8()))

    # space-to-depth stem lowering (same params tree)
    model_s2d = I3D(modality="rgb", s2d_stem=True, dtype=dtype)

    def full_s2d(p, x):
        return model_s2d.apply({"params": p}, x, features=True)

    time_fn("full_s2d", jax.jit(full_s2d), lambda: (params, frames()))

    stem = I3DPrefix(n_ops=1, dtype=dtype)
    stem_params = jax.device_put(
        stem.init(jax.random.PRNGKey(0), jnp.zeros((1, 16, 224, 224, 3)))["params"])
    from video_features_tpu.models.layers import S2DStemConv

    s2d_conv = S2DStemConv(64, dtype=dtype)
    kernel_tree = {"kernel": stem_params["conv3d_1a_7x7"]["conv3d"]["kernel"]}

    def stem_s2d(p, x):
        return s2d_conv.apply({"params": p}, x)

    time_fn("stem_s2d_conv", jax.jit(stem_s2d), lambda: (kernel_tree, frames()))


if __name__ == "__main__":
    main()
