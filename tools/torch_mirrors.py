"""Minimal torch mirrors of the pretrained architectures, for numerical parity tests.

torchvision is not installed in this image, so these re-create the exact architectures
(state_dict-name-compatible with torchvision / the reference checkpoints) to generate
random-weight golden outputs. They are test fixtures, not part of the framework — the
framework's models live in :mod:`video_features_tpu.models` (Flax).

State-dict compatibility means: a real pretrained torchvision/reference checkpoint
loads into these modules unchanged, and conversely the converters in
:mod:`video_features_tpu.weights` accept these modules' state_dicts.
"""

from __future__ import annotations

import torch
import torch.nn as nn


class Bottleneck(nn.Module):
    expansion = 4

    def __init__(self, inplanes, planes, stride=1, downsample=None):
        super().__init__()
        self.conv1 = nn.Conv2d(inplanes, planes, 1, bias=False)
        self.bn1 = nn.BatchNorm2d(planes)
        self.conv2 = nn.Conv2d(planes, planes, 3, stride=stride, padding=1, bias=False)
        self.bn2 = nn.BatchNorm2d(planes)
        self.conv3 = nn.Conv2d(planes, planes * 4, 1, bias=False)
        self.bn3 = nn.BatchNorm2d(planes * 4)
        self.relu = nn.ReLU(inplace=True)
        self.downsample = downsample
        self.stride = stride

    def forward(self, x):
        identity = x
        out = self.relu(self.bn1(self.conv1(x)))
        out = self.relu(self.bn2(self.conv2(out)))
        out = self.bn3(self.conv3(out))
        if self.downsample is not None:
            identity = self.downsample(x)
        return self.relu(out + identity)


class ResNet50(nn.Module):
    """torchvision-compatible resnet50 (v1.5: stride on the 3x3)."""

    def __init__(self, num_classes=1000):
        super().__init__()
        self.inplanes = 64
        self.conv1 = nn.Conv2d(3, 64, 7, stride=2, padding=3, bias=False)
        self.bn1 = nn.BatchNorm2d(64)
        self.relu = nn.ReLU(inplace=True)
        self.maxpool = nn.MaxPool2d(3, stride=2, padding=1)
        self.layer1 = self._make_layer(64, 3)
        self.layer2 = self._make_layer(128, 4, stride=2)
        self.layer3 = self._make_layer(256, 6, stride=2)
        self.layer4 = self._make_layer(512, 3, stride=2)
        self.avgpool = nn.AdaptiveAvgPool2d((1, 1))
        self.fc = nn.Linear(512 * 4, num_classes)

    def _make_layer(self, planes, blocks, stride=1):
        downsample = None
        if stride != 1 or self.inplanes != planes * 4:
            downsample = nn.Sequential(
                nn.Conv2d(self.inplanes, planes * 4, 1, stride=stride, bias=False),
                nn.BatchNorm2d(planes * 4),
            )
        layers = [Bottleneck(self.inplanes, planes, stride, downsample)]
        self.inplanes = planes * 4
        layers += [Bottleneck(self.inplanes, planes) for _ in range(1, blocks)]
        return nn.Sequential(*layers)

    def forward(self, x, features=True):
        x = self.maxpool(self.relu(self.bn1(self.conv1(x))))
        x = self.layer4(self.layer3(self.layer2(self.layer1(x))))
        x = torch.flatten(self.avgpool(x), 1)
        return x if features else self.fc(x)


def random_init_(model: nn.Module, seed: int = 0) -> nn.Module:
    """Randomize all parameters and BN running stats so parity tests are non-trivial."""
    g = torch.Generator().manual_seed(seed)
    state = model.state_dict()
    for name, t in state.items():
        if t.dtype.is_floating_point:
            if name.endswith("running_var"):
                t.copy_(torch.rand(t.shape, generator=g) + 0.5)
            else:
                t.copy_(torch.randn(t.shape, generator=g) * 0.05)
    model.load_state_dict(state)
    model.eval()
    return model
