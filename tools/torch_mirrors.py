"""Minimal torch mirrors of the pretrained architectures, for numerical parity tests.

torchvision is not installed in this image, so these re-create the exact architectures
(state_dict-name-compatible with torchvision / the reference checkpoints) to generate
random-weight golden outputs. They are test fixtures, not part of the framework — the
framework's models live in :mod:`video_features_tpu.models` (Flax).

State-dict compatibility means: a real pretrained torchvision/reference checkpoint
loads into these modules unchanged, and conversely the converters in
:mod:`video_features_tpu.weights` accept these modules' state_dicts.
"""

from __future__ import annotations

import torch
import torch.nn as nn


class Bottleneck(nn.Module):
    expansion = 4

    def __init__(self, inplanes, planes, stride=1, downsample=None):
        super().__init__()
        self.conv1 = nn.Conv2d(inplanes, planes, 1, bias=False)
        self.bn1 = nn.BatchNorm2d(planes)
        self.conv2 = nn.Conv2d(planes, planes, 3, stride=stride, padding=1, bias=False)
        self.bn2 = nn.BatchNorm2d(planes)
        self.conv3 = nn.Conv2d(planes, planes * 4, 1, bias=False)
        self.bn3 = nn.BatchNorm2d(planes * 4)
        self.relu = nn.ReLU(inplace=True)
        self.downsample = downsample
        self.stride = stride

    def forward(self, x):
        identity = x
        out = self.relu(self.bn1(self.conv1(x)))
        out = self.relu(self.bn2(self.conv2(out)))
        out = self.bn3(self.conv3(out))
        if self.downsample is not None:
            identity = self.downsample(x)
        return self.relu(out + identity)


class ResNet50(nn.Module):
    """torchvision-compatible resnet50 (v1.5: stride on the 3x3)."""

    def __init__(self, num_classes=1000):
        super().__init__()
        self.inplanes = 64
        self.conv1 = nn.Conv2d(3, 64, 7, stride=2, padding=3, bias=False)
        self.bn1 = nn.BatchNorm2d(64)
        self.relu = nn.ReLU(inplace=True)
        self.maxpool = nn.MaxPool2d(3, stride=2, padding=1)
        self.layer1 = self._make_layer(64, 3)
        self.layer2 = self._make_layer(128, 4, stride=2)
        self.layer3 = self._make_layer(256, 6, stride=2)
        self.layer4 = self._make_layer(512, 3, stride=2)
        self.avgpool = nn.AdaptiveAvgPool2d((1, 1))
        self.fc = nn.Linear(512 * 4, num_classes)

    def _make_layer(self, planes, blocks, stride=1):
        downsample = None
        if stride != 1 or self.inplanes != planes * 4:
            downsample = nn.Sequential(
                nn.Conv2d(self.inplanes, planes * 4, 1, stride=stride, bias=False),
                nn.BatchNorm2d(planes * 4),
            )
        layers = [Bottleneck(self.inplanes, planes, stride, downsample)]
        self.inplanes = planes * 4
        layers += [Bottleneck(self.inplanes, planes) for _ in range(1, blocks)]
        return nn.Sequential(*layers)

    def forward(self, x, features=True):
        x = self.maxpool(self.relu(self.bn1(self.conv1(x))))
        x = self.layer4(self.layer3(self.layer2(self.layer1(x))))
        x = torch.flatten(self.avgpool(x), 1)
        return x if features else self.fc(x)


# ---------------------------------------------------------------------------
# I3D: functional mirror (no nn.Module graph) driven by the SAME spec table as
# the Flax model (imported, not copied). Consumes/produces reference-named
# state_dicts (conv3d_1a_7x7.conv3d.weight, mixed_3b.branch_1.0..., ...).
# ---------------------------------------------------------------------------

import torch.nn.functional as F

from video_features_tpu.models.i3d import I3D_STEM as I3D_LAYERS


def _tf_same_pad_5d(kernel, stride):
    """F.pad arg (w_lo, w_hi, h_lo, h_hi, t_lo, t_hi) for the (k - s) SAME rule."""
    flat = []
    for k, s in zip(reversed(kernel), reversed(stride)):
        p = max(k - s, 0)
        flat += [p // 2, p - p // 2]
    return flat


def _i3d_unit(sd, prefix, x, kernel=(1, 1, 1), stride=(1, 1, 1), bn=True, act=True):
    x = F.pad(x, _tf_same_pad_5d(kernel, stride))
    x = F.conv3d(x, sd[f"{prefix}.conv3d.weight"], sd.get(f"{prefix}.conv3d.bias"),
                 stride=tuple(stride))
    if bn:
        x = F.batch_norm(
            x,
            sd[f"{prefix}.batch3d.running_mean"],
            sd[f"{prefix}.batch3d.running_var"],
            sd[f"{prefix}.batch3d.weight"],
            sd[f"{prefix}.batch3d.bias"],
            training=False,
        )
    return F.relu(x) if act else x


def _i3d_pool(x, kernel, stride):
    x = F.pad(x, _tf_same_pad_5d(kernel, stride))
    return F.max_pool3d(x, kernel, stride, ceil_mode=True)


def i3d_forward(sd, x, features=True, num_classes=400):
    """Functional I3D on (B, C, T, H, W); mirrors i3d_net.py numerics for parity."""
    with torch.no_grad():
        for layer in I3D_LAYERS:
            kind, name = layer[0], layer[1]
            if kind == "conv":
                _, _, _, kernel, stride = layer
                x = _i3d_unit(sd, name, x, kernel, stride)
            elif kind == "pool":
                _, _, kernel, stride = layer
                x = _i3d_pool(x, kernel, stride)
            else:
                b0 = _i3d_unit(sd, f"{name}.branch_0", x)
                b1 = _i3d_unit(sd, f"{name}.branch_1.1",
                               _i3d_unit(sd, f"{name}.branch_1.0", x), (3, 3, 3))
                b2 = _i3d_unit(sd, f"{name}.branch_2.1",
                               _i3d_unit(sd, f"{name}.branch_2.0", x), (3, 3, 3))
                b3 = _i3d_unit(sd, f"{name}.branch_3.1", _i3d_pool(x, (3, 3, 3), (1, 1, 1)))
                x = torch.cat([b0, b1, b2, b3], dim=1)
        # reference kernel (2,7,7) == (2, H, W) at the supported 224-crop geometry
        x = F.avg_pool3d(x, (2, x.shape[3], x.shape[4]), (1, 1, 1))
        if features:
            return x.squeeze(3).squeeze(3).mean(2)
        x = _i3d_unit(sd, "conv3d_0c_1x1", x, bn=False, act=False)
        logits = x.squeeze(3).squeeze(3).mean(2)
        return torch.softmax(logits, 1), logits


def i3d_random_state_dict(modality="rgb", num_classes=400, seed=0):
    """Reference-named random state_dict exercising converter + forward parity."""
    g = torch.Generator().manual_seed(seed)

    def unit(prefix, cin, cout, kernel, sd, bn=True, bias=False):
        sd[f"{prefix}.conv3d.weight"] = torch.randn((cout, cin, *kernel), generator=g) * 0.05
        if bias:
            sd[f"{prefix}.conv3d.bias"] = torch.randn((cout,), generator=g) * 0.05
        if bn:
            sd[f"{prefix}.batch3d.weight"] = torch.rand((cout,), generator=g) + 0.5
            sd[f"{prefix}.batch3d.bias"] = torch.randn((cout,), generator=g) * 0.05
            sd[f"{prefix}.batch3d.running_mean"] = torch.randn((cout,), generator=g) * 0.05
            sd[f"{prefix}.batch3d.running_var"] = torch.rand((cout,), generator=g) + 0.5

    sd = {}
    cin = {"rgb": 3, "flow": 2}[modality]
    for layer in I3D_LAYERS:
        kind, name = layer[0], layer[1]
        if kind == "conv":
            _, _, cout, kernel, _ = layer
            unit(name, cin, cout, kernel, sd)
            cin = cout
        elif kind == "mixed":
            c0, c1r, c1, c2r, c2, c3 = layer[2]
            unit(f"{name}.branch_0", cin, c0, (1, 1, 1), sd)
            unit(f"{name}.branch_1.0", cin, c1r, (1, 1, 1), sd)
            unit(f"{name}.branch_1.1", c1r, c1, (3, 3, 3), sd)
            unit(f"{name}.branch_2.0", cin, c2r, (1, 1, 1), sd)
            unit(f"{name}.branch_2.1", c2r, c2, (3, 3, 3), sd)
            unit(f"{name}.branch_3.1", cin, c3, (1, 1, 1), sd)
            cin = c0 + c1 + c2 + c3
    unit("conv3d_0c_1x1", 1024, num_classes, (1, 1, 1), sd, bn=False, bias=True)
    return sd


def random_init_(model: nn.Module, seed: int = 0) -> nn.Module:
    """Randomize all parameters and BN running stats so parity tests are non-trivial."""
    g = torch.Generator().manual_seed(seed)
    state = model.state_dict()
    for name, t in state.items():
        if t.dtype.is_floating_point:
            if name.endswith("running_var"):
                t.copy_(torch.rand(t.shape, generator=g) + 0.5)
            else:
                t.copy_(torch.randn(t.shape, generator=g) * 0.05)
    model.load_state_dict(state)
    model.eval()
    return model
