"""Minimal torch mirrors of the pretrained architectures, for numerical parity tests.

torchvision is not installed in this image, so these re-create the exact architectures
(state_dict-name-compatible with torchvision / the reference checkpoints) to generate
random-weight golden outputs. They are test fixtures, not part of the framework — the
framework's models live in :mod:`video_features_tpu.models` (Flax).

State-dict compatibility means: a real pretrained torchvision/reference checkpoint
loads into these modules unchanged, and conversely the converters in
:mod:`video_features_tpu.weights` accept these modules' state_dicts.
"""

from __future__ import annotations

import torch
import torch.nn as nn


class Bottleneck(nn.Module):
    expansion = 4

    def __init__(self, inplanes, planes, stride=1, downsample=None):
        super().__init__()
        self.conv1 = nn.Conv2d(inplanes, planes, 1, bias=False)
        self.bn1 = nn.BatchNorm2d(planes)
        self.conv2 = nn.Conv2d(planes, planes, 3, stride=stride, padding=1, bias=False)
        self.bn2 = nn.BatchNorm2d(planes)
        self.conv3 = nn.Conv2d(planes, planes * 4, 1, bias=False)
        self.bn3 = nn.BatchNorm2d(planes * 4)
        self.relu = nn.ReLU(inplace=True)
        self.downsample = downsample
        self.stride = stride

    def forward(self, x):
        identity = x
        out = self.relu(self.bn1(self.conv1(x)))
        out = self.relu(self.bn2(self.conv2(out)))
        out = self.bn3(self.conv3(out))
        if self.downsample is not None:
            identity = self.downsample(x)
        return self.relu(out + identity)


class ResNet50(nn.Module):
    """torchvision-compatible resnet50 (v1.5: stride on the 3x3)."""

    def __init__(self, num_classes=1000):
        super().__init__()
        self.inplanes = 64
        self.conv1 = nn.Conv2d(3, 64, 7, stride=2, padding=3, bias=False)
        self.bn1 = nn.BatchNorm2d(64)
        self.relu = nn.ReLU(inplace=True)
        self.maxpool = nn.MaxPool2d(3, stride=2, padding=1)
        self.layer1 = self._make_layer(64, 3)
        self.layer2 = self._make_layer(128, 4, stride=2)
        self.layer3 = self._make_layer(256, 6, stride=2)
        self.layer4 = self._make_layer(512, 3, stride=2)
        self.avgpool = nn.AdaptiveAvgPool2d((1, 1))
        self.fc = nn.Linear(512 * 4, num_classes)

    def _make_layer(self, planes, blocks, stride=1):
        downsample = None
        if stride != 1 or self.inplanes != planes * 4:
            downsample = nn.Sequential(
                nn.Conv2d(self.inplanes, planes * 4, 1, stride=stride, bias=False),
                nn.BatchNorm2d(planes * 4),
            )
        layers = [Bottleneck(self.inplanes, planes, stride, downsample)]
        self.inplanes = planes * 4
        layers += [Bottleneck(self.inplanes, planes) for _ in range(1, blocks)]
        return nn.Sequential(*layers)

    def forward(self, x, features=True):
        x = self.maxpool(self.relu(self.bn1(self.conv1(x))))
        x = self.layer4(self.layer3(self.layer2(self.layer1(x))))
        x = torch.flatten(self.avgpool(x), 1)
        return x if features else self.fc(x)


# ---------------------------------------------------------------------------
# I3D: functional mirror (no nn.Module graph). The layer table below is
# transcribed INDEPENDENTLY from the reference source
# (/root/reference/models/i3d/i3d_src/i3d_net.py:179-224) — deliberately NOT
# imported from video_features_tpu.models.i3d, so a wrong channel count or
# missing branch in the Flax spec table fails parity instead of propagating
# into the oracle (tests/test_mirror_independence.py cross-checks the tables).
# Consumes/produces reference-named state_dicts (conv3d_1a_7x7.conv3d.weight,
# mixed_3b.branch_1.0..., ...).
# ---------------------------------------------------------------------------

import torch.nn.functional as F

# (op, name, out_channels, kernel, stride) / (pool, name, kernel, stride) /
# (mixed, name, (b0, b1_reduce, b1, b2_reduce, b2, b3)); i3d_net.py:179-224
I3D_LAYERS = (
    ("conv", "conv3d_1a_7x7", 64, (7, 7, 7), (2, 2, 2)),
    ("pool", "maxPool3d_2a_3x3", (1, 3, 3), (1, 2, 2)),
    ("conv", "conv3d_2b_1x1", 64, (1, 1, 1), (1, 1, 1)),
    ("conv", "conv3d_2c_3x3", 192, (3, 3, 3), (1, 1, 1)),
    ("pool", "maxPool3d_3a_3x3", (1, 3, 3), (1, 2, 2)),
    ("mixed", "mixed_3b", (64, 96, 128, 16, 32, 32)),
    ("mixed", "mixed_3c", (128, 128, 192, 32, 96, 64)),
    ("pool", "maxPool3d_4a_3x3", (3, 3, 3), (2, 2, 2)),
    ("mixed", "mixed_4b", (192, 96, 208, 16, 48, 64)),
    ("mixed", "mixed_4c", (160, 112, 224, 24, 64, 64)),
    ("mixed", "mixed_4d", (128, 128, 256, 24, 64, 64)),
    ("mixed", "mixed_4e", (112, 144, 288, 32, 64, 64)),
    ("mixed", "mixed_4f", (256, 160, 320, 32, 128, 128)),
    ("pool", "maxPool3d_5a_2x2", (2, 2, 2), (2, 2, 2)),
    ("mixed", "mixed_5b", (256, 160, 320, 32, 128, 128)),
    ("mixed", "mixed_5c", (384, 192, 384, 48, 128, 128)),
)


def _tf_same_pad_5d(kernel, stride):
    """F.pad arg (w_lo, w_hi, h_lo, h_hi, t_lo, t_hi) for the (k - s) SAME rule."""
    flat = []
    for k, s in zip(reversed(kernel), reversed(stride)):
        p = max(k - s, 0)
        flat += [p // 2, p - p // 2]
    return flat


def _i3d_unit(sd, prefix, x, kernel=(1, 1, 1), stride=(1, 1, 1), bn=True, act=True):
    x = F.pad(x, _tf_same_pad_5d(kernel, stride))
    x = F.conv3d(x, sd[f"{prefix}.conv3d.weight"], sd.get(f"{prefix}.conv3d.bias"),
                 stride=tuple(stride))
    if bn:
        x = F.batch_norm(
            x,
            sd[f"{prefix}.batch3d.running_mean"],
            sd[f"{prefix}.batch3d.running_var"],
            sd[f"{prefix}.batch3d.weight"],
            sd[f"{prefix}.batch3d.bias"],
            training=False,
        )
    return F.relu(x) if act else x


def _i3d_pool(x, kernel, stride):
    x = F.pad(x, _tf_same_pad_5d(kernel, stride))
    return F.max_pool3d(x, kernel, stride, ceil_mode=True)


def i3d_forward(sd, x, features=True, taps=None):
    """Functional I3D on (B, C, T, H, W); mirrors i3d_net.py numerics for parity.

    ``taps``: debug-only dict filled with each named layer's output (NCTHW) for
    the layer-diff parity harness (tools/layer_diff.py)."""
    with torch.no_grad():
        for layer in I3D_LAYERS:
            kind, name = layer[0], layer[1]
            if kind == "conv":
                _, _, _, kernel, stride = layer
                x = _i3d_unit(sd, name, x, kernel, stride)
            elif kind == "pool":
                _, _, kernel, stride = layer
                x = _i3d_pool(x, kernel, stride)
            else:
                b0 = _i3d_unit(sd, f"{name}.branch_0", x)
                b1 = _i3d_unit(sd, f"{name}.branch_1.1",
                               _i3d_unit(sd, f"{name}.branch_1.0", x), (3, 3, 3))
                b2 = _i3d_unit(sd, f"{name}.branch_2.1",
                               _i3d_unit(sd, f"{name}.branch_2.0", x), (3, 3, 3))
                b3 = _i3d_unit(sd, f"{name}.branch_3.1", _i3d_pool(x, (3, 3, 3), (1, 1, 1)))
                x = torch.cat([b0, b1, b2, b3], dim=1)
            if taps is not None:
                taps[name] = x
        # reference kernel (2,7,7) == (2, H, W) at the supported 224-crop geometry
        x = F.avg_pool3d(x, (2, x.shape[3], x.shape[4]), (1, 1, 1))
        if features:
            return x.squeeze(3).squeeze(3).mean(2)
        x = _i3d_unit(sd, "conv3d_0c_1x1", x, bn=False, act=False)
        logits = x.squeeze(3).squeeze(3).mean(2)
        return torch.softmax(logits, 1), logits


def i3d_random_state_dict(modality="rgb", num_classes=400, seed=0):
    """Reference-named random state_dict exercising converter + forward parity."""
    g = torch.Generator().manual_seed(seed)

    def unit(prefix, cin, cout, kernel, sd, bn=True, bias=False):
        sd[f"{prefix}.conv3d.weight"] = torch.randn((cout, cin, *kernel), generator=g) * 0.05
        if bias:
            sd[f"{prefix}.conv3d.bias"] = torch.randn((cout,), generator=g) * 0.05
        if bn:
            sd[f"{prefix}.batch3d.weight"] = torch.rand((cout,), generator=g) + 0.5
            sd[f"{prefix}.batch3d.bias"] = torch.randn((cout,), generator=g) * 0.05
            sd[f"{prefix}.batch3d.running_mean"] = torch.randn((cout,), generator=g) * 0.05
            sd[f"{prefix}.batch3d.running_var"] = torch.rand((cout,), generator=g) + 0.5

    sd = {}
    cin = {"rgb": 3, "flow": 2}[modality]
    for layer in I3D_LAYERS:
        kind, name = layer[0], layer[1]
        if kind == "conv":
            _, _, cout, kernel, _ = layer
            unit(name, cin, cout, kernel, sd)
            cin = cout
        elif kind == "mixed":
            c0, c1r, c1, c2r, c2, c3 = layer[2]
            unit(f"{name}.branch_0", cin, c0, (1, 1, 1), sd)
            unit(f"{name}.branch_1.0", cin, c1r, (1, 1, 1), sd)
            unit(f"{name}.branch_1.1", c1r, c1, (3, 3, 3), sd)
            unit(f"{name}.branch_2.0", cin, c2r, (1, 1, 1), sd)
            unit(f"{name}.branch_2.1", c2r, c2, (3, 3, 3), sd)
            unit(f"{name}.branch_3.1", cin, c3, (1, 1, 1), sd)
            cin = c0 + c1 + c2 + c3
    unit("conv3d_0c_1x1", 1024, num_classes, (1, 1, 1), sd, bn=False, bias=True)
    return sd


# ---------------------------------------------------------------------------
# RAFT: functional torch mirror of the reference semantics (raft_src/). The
# shape table is transcribed INDEPENDENTLY from the reference source — NOT
# imported from video_features_tpu.models.raft — so the oracle cannot inherit
# a Flax spec-table bug. Sources: BasicEncoder channels 64→64/96/128→out
# (extractor.py:118-148), BasicMotionEncoder (update.py:83-91), SepConvGRU
# (update.py:37-46), FlowHead (update.py:10-14), mask head (update.py:124-128),
# RAFT dims hidden=context=128, corr 4 levels radius 4 (raft.py:55-67).
# ---------------------------------------------------------------------------


def raft_conv_shapes():
    """name → (cin, cout, kh, kw) conv / (c,) norm, reference state_dict names."""
    shapes = {}

    def encoder(prefix, out_dim, batch_norm):
        # conv1: 3→64 k7 s2 (extractor.py:135); residual stages 64,96,128 of two
        # blocks each, stride 2 on the first block of layer2/3 (:137-142)
        shapes[f"{prefix}.conv1"] = (3, 64, 7, 7)
        if batch_norm:
            shapes[f"{prefix}.norm1"] = (64,)
        cin = 64
        for stage, dim, stride in (("layer1", 64, 1), ("layer2", 96, 2), ("layer3", 128, 2)):
            for blk in (0, 1):
                s = stride if blk == 0 else 1
                p = f"{prefix}.{stage}.{blk}"
                shapes[f"{p}.conv1"] = (cin if blk == 0 else dim, dim, 3, 3)
                shapes[f"{p}.conv2"] = (dim, dim, 3, 3)
                if batch_norm:
                    shapes[f"{p}.norm1"] = (dim,)
                    shapes[f"{p}.norm2"] = (dim,)
                if blk == 0 and s != 1:
                    shapes[f"{p}.downsample.0"] = (cin, dim, 1, 1)
                    if batch_norm:
                        shapes[f"{p}.norm3"] = (dim,)
            cin = dim
        shapes[f"{prefix}.conv2"] = (128, out_dim, 1, 1)  # extractor.py:144

    encoder("fnet", 256, batch_norm=False)   # raft.py:129 output_dim=256, instance norm
    encoder("cnet", 128 + 128, batch_norm=True)  # hdim+cdim (raft.py:58-59)

    cor_planes = 4 * (2 * 4 + 1) ** 2  # levels × (2r+1)², update.py:85-86 → 324
    ub = "update_block"
    shapes[f"{ub}.encoder.convc1"] = (cor_planes, 256, 1, 1)  # update.py:87
    shapes[f"{ub}.encoder.convc2"] = (256, 192, 3, 3)         # update.py:88
    shapes[f"{ub}.encoder.convf1"] = (2, 128, 7, 7)           # update.py:89
    shapes[f"{ub}.encoder.convf2"] = (128, 64, 3, 3)          # update.py:90
    shapes[f"{ub}.encoder.conv"] = (64 + 192, 128 - 2, 3, 3)  # update.py:91
    gru_in = 128 + (128 + 128)  # hidden + input_dim(128+hidden), update.py:37-38,122
    for sfx, k in (("1", (1, 5)), ("2", (5, 1))):  # update.py:40-46
        for gate in ("convz", "convr", "convq"):
            shapes[f"{ub}.gru.{gate}{sfx}"] = (gru_in, 128, *k)
    shapes[f"{ub}.flow_head.conv1"] = (128, 256, 3, 3)  # update.py:13 hidden=256
    shapes[f"{ub}.flow_head.conv2"] = (256, 2, 3, 3)    # update.py:14
    shapes[f"{ub}.mask.0"] = (128, 256, 3, 3)           # update.py:126
    shapes[f"{ub}.mask.2"] = (256, 64 * 9, 1, 1)        # update.py:128
    return shapes


def raft_random_state_dict(seed: int = 0):
    """Reference-named random state_dict (no 'module.' prefix)."""
    g = torch.Generator().manual_seed(seed)
    sd = {}
    for name, shape in raft_conv_shapes().items():
        if len(shape) == 1:  # batch norm
            c = shape[0]
            sd[f"{name}.weight"] = torch.rand(c, generator=g) + 0.5
            sd[f"{name}.bias"] = torch.randn(c, generator=g) * 0.05
            sd[f"{name}.running_mean"] = torch.randn(c, generator=g) * 0.05
            sd[f"{name}.running_var"] = torch.rand(c, generator=g) + 0.5
        else:
            cin, cout, kh, kw = shape
            sd[f"{name}.weight"] = torch.randn((cout, cin, kh, kw), generator=g) * 0.05
            sd[f"{name}.bias"] = torch.randn(cout, generator=g) * 0.05
    return sd


def _rconv(sd, name, x, stride=1, padding=0):
    return F.conv2d(x, sd[f"{name}.weight"], sd[f"{name}.bias"], stride, padding)


def _rnorm(sd, name, x, kind):
    if kind == "instance":
        return F.instance_norm(x)
    if kind == "batch":
        return F.batch_norm(x, sd[f"{name}.running_mean"], sd[f"{name}.running_var"],
                            sd[f"{name}.weight"], sd[f"{name}.bias"], training=False)
    return x


def _raft_encoder(sd, prefix, x, kind):
    x = F.relu(_rnorm(sd, f"{prefix}.norm1", _rconv(sd, f"{prefix}.conv1", x, 2, 3), kind))
    for stage, stride in (("layer1", 1), ("layer2", 2), ("layer3", 2)):
        for blk in (0, 1):
            s = stride if blk == 0 else 1
            p = f"{prefix}.{stage}.{blk}"
            y = F.relu(_rnorm(sd, f"{p}.norm1", _rconv(sd, f"{p}.conv1", x, s, 1), kind))
            y = F.relu(_rnorm(sd, f"{p}.norm2", _rconv(sd, f"{p}.conv2", y, 1, 1), kind))
            if s != 1:
                x = _rnorm(sd, f"{p}.norm3", _rconv(sd, f"{p}.downsample.0", x, s, 0), kind)
            x = F.relu(x + y)
    return _rconv(sd, f"{prefix}.conv2", x, 1, 0)


def _raft_bilinear(img, coords):
    """Reference bilinear_sampler: pixel coords → grid_sample align_corners=True."""
    H, W = img.shape[-2:]
    xg = 2 * coords[..., 0] / (W - 1) - 1
    yg = 2 * coords[..., 1] / (H - 1) - 1
    return F.grid_sample(img, torch.stack([xg, yg], -1), align_corners=True)


def raft_torch_forward(sd, image1, image2, iters=20, taps=None):
    """(B, 3, H, W) float RGB [0,255], H,W /8 → (B, 2, H, W) flow. Mirrors
    raft.py:115-174 numerics including the delta-grid dx/dy swap (corr.py:37-43).

    ``taps``: debug-only dict of per-stage activations for tools/layer_diff.py."""
    with torch.no_grad():
        x1 = 2 * (image1 / 255.0) - 1.0
        x2 = 2 * (image2 / 255.0) - 1.0
        f1 = _raft_encoder(sd, "fnet", x1, "instance").float()
        f2 = _raft_encoder(sd, "fnet", x2, "instance").float()

        B, D, H, W = f1.shape
        corr = torch.matmul(f1.view(B, D, -1).transpose(1, 2), f2.view(B, D, -1))
        corr = corr.view(B * H * W, 1, H, W) / (D ** 0.5)
        pyramid = [corr]
        for _ in range(3):
            corr = F.avg_pool2d(corr, 2, stride=2)
            pyramid.append(corr)

        cnet = _raft_encoder(sd, "cnet", x1, "batch")
        net, inp = torch.tanh(cnet[:, :128]), F.relu(cnet[:, 128:])
        if taps is not None:
            taps["fnet1"], taps["fnet2"], taps["cnet"] = f1, f2, cnet
            taps["corr_l0"] = pyramid[0]

        ys, xs = torch.meshgrid(torch.arange(H), torch.arange(W), indexing="ij")
        coords0 = torch.stack([xs, ys], 0).float()[None].repeat(B, 1, 1, 1)
        coords1 = coords0.clone()

        r = 4
        d = torch.linspace(-r, r, 2 * r + 1)
        # reference delta swap: grid axis 0 carries the x displacement
        delta = torch.stack(torch.meshgrid(d, d, indexing="ij"), dim=-1)  # (9,9,(dx,dy))

        for _ in range(iters):
            pts = coords1.permute(0, 2, 3, 1).reshape(B * H * W, 1, 1, 2)
            out = []
            for i, c in enumerate(pyramid):
                lvl = pts / 2 ** i + delta.view(1, 9, 9, 2)
                smp = _raft_bilinear(c, lvl)  # (BHW, 1, 9, 9)
                out.append(smp.view(B, H, W, 81))
            corr_feat = torch.cat(out, -1).permute(0, 3, 1, 2)

            flow = coords1 - coords0
            cor = F.relu(_rconv(sd, "update_block.encoder.convc1", corr_feat))
            cor = F.relu(_rconv(sd, "update_block.encoder.convc2", cor, 1, 1))
            flo = F.relu(_rconv(sd, "update_block.encoder.convf1", flow, 1, 3))
            flo = F.relu(_rconv(sd, "update_block.encoder.convf2", flo, 1, 1))
            mot = F.relu(_rconv(sd, "update_block.encoder.conv", torch.cat([cor, flo], 1), 1, 1))
            x = torch.cat([inp, torch.cat([mot, flow], 1)], 1)

            h = net
            for sfx, pad in (("1", (0, 2)), ("2", (2, 0))):
                hx = torch.cat([h, x], 1)
                z = torch.sigmoid(F.conv2d(hx, sd[f"update_block.gru.convz{sfx}.weight"],
                                           sd[f"update_block.gru.convz{sfx}.bias"], 1, pad))
                rr = torch.sigmoid(F.conv2d(hx, sd[f"update_block.gru.convr{sfx}.weight"],
                                            sd[f"update_block.gru.convr{sfx}.bias"], 1, pad))
                q = torch.tanh(F.conv2d(torch.cat([rr * h, x], 1),
                                        sd[f"update_block.gru.convq{sfx}.weight"],
                                        sd[f"update_block.gru.convq{sfx}.bias"], 1, pad))
                h = (1 - z) * h + z * q
            net = h
            delta_flow = _rconv(sd, "update_block.flow_head.conv2",
                                F.relu(_rconv(sd, "update_block.flow_head.conv1", net, 1, 1)), 1, 1)
            coords1 = coords1 + delta_flow
            if taps is not None:
                taps[f"flow_iter{len([k for k in taps if k.startswith('flow_iter')])}"] = (
                    coords1 - coords0
                )

        mask = 0.25 * _rconv(sd, "update_block.mask.2",
                             F.relu(_rconv(sd, "update_block.mask.0", net, 1, 1)))
        # convex upsample (raft.py:100-111)
        flow = coords1 - coords0
        m = mask.view(B, 1, 9, 8, 8, H, W)
        m = torch.softmax(m, dim=2)
        up = F.unfold(8 * flow, [3, 3], padding=1).view(B, 2, 9, 1, 1, H, W)
        up = torch.sum(m * up, dim=2).permute(0, 1, 4, 2, 5, 3)
        return up.reshape(B, 2, 8 * H, 8 * W)


# ---------------------------------------------------------------------------
# PWC-Net: functional torch mirror of the reference semantics (pwc_src/). The
# tables below are transcribed INDEPENDENTLY from pwc_net.py — NOT imported
# from video_features_tpu.models.pwc. torch-1.2 grid_sample semantics
# (align_corners=True) per the pinned conda_env_pwc.yml.
# ---------------------------------------------------------------------------

# PWCNet decoder attribute per pyramid level (pwc_net.py:215-221)
LEVEL_NAMES = {2: "moduleTwo", 3: "moduleThr", 4: "moduleFou", 5: "moduleFiv", 6: "moduleSix"}
# dblBackward warp scaling, indexed by the level whose decoder consumes it
# (pwc_net.py:124: [None,None,None,5.0,2.5,1.25,0.625,None][intLevel+1])
DEC_BACKWARD = {2: 5.0, 3: 2.5, 4: 1.25, 5: 0.625}

# Extractor per-level (out_channels) ×3 convs each (pwc_net.py:48-101)
_PWC_EXTRACTOR_CH = (16, 32, 64, 96, 128, 196)
# Decoder input width per level: 81 corr (+ feat + 2 flow + 2 upfeat below L6)
# (pwc_net.py:120-121: intCurrent = [None,None,81+32+2+2,81+64+2+2,81+96+2+2,81+128+2+2,81,None])
_PWC_DEC_CURRENT = {2: 81 + 32 + 4, 3: 81 + 64 + 4, 4: 81 + 96 + 4, 5: 81 + 128 + 4, 6: 81}
# DenseNet decoder head widths (pwc_net.py:128-158)
_PWC_DEC_OUT = (128, 128, 96, 64, 32)


def pwc_conv_shapes():
    """name → (cin, cout, kh, kw), 'T'-prefixed for ConvTranspose2d weights."""
    shapes = {}
    cin = 3
    for name, cout in zip(
        ("moduleOne", "moduleTwo", "moduleThr", "moduleFou", "moduleFiv", "moduleSix"),
        _PWC_EXTRACTOR_CH,
    ):
        p = f"moduleExtractor.{name}"
        shapes[f"{p}.0"] = (cin, cout, 3, 3)   # stride-2 conv (pwc_net.py:49)
        shapes[f"{p}.2"] = (cout, cout, 3, 3)
        shapes[f"{p}.4"] = (cout, cout, 3, 3)
        cin = cout

    for level in (6, 5, 4, 3, 2):
        mod = LEVEL_NAMES[level]
        cur = _PWC_DEC_CURRENT[level]
        if level < 6:
            prev = _PWC_DEC_CURRENT[level + 1]
            # ConvTranspose2d weights are (cin, cout, kh, kw) (pwc_net.py:123-124)
            shapes[f"{mod}.moduleUpflow"] = ("T", 2, 2, 4, 4)
            shapes[f"{mod}.moduleUpfeat"] = ("T", prev + sum(_PWC_DEC_OUT), 2, 4, 4)
        feat = cur
        for name, cout in zip(("moduleOne", "moduleTwo", "moduleThr", "moduleFou", "moduleFiv"),
                              _PWC_DEC_OUT):
            shapes[f"{mod}.{name}.0"] = (feat, cout, 3, 3)
            feat += cout
        shapes[f"{mod}.moduleSix.0"] = (feat, 2, 3, 3)

    # Refiner: 7 dilated convs from the level-2 dense feature (pwc_net.py:193-210)
    refiner_in = _PWC_DEC_CURRENT[2] + sum(_PWC_DEC_OUT)  # 565
    chans = (refiner_in, 128, 128, 128, 96, 64, 32, 2)
    for i, idx in enumerate(("0", "2", "4", "6", "8", "10", "12")):
        shapes[f"moduleRefiner.moduleMain.{idx}"] = (chans[i], chans[i + 1], 3, 3)
    return shapes


def pwc_random_state_dict(seed: int = 0):
    g = torch.Generator().manual_seed(seed)
    sd = {}
    for name, shape in pwc_conv_shapes().items():
        if shape[0] == "T":
            _, cin, cout, kh, kw = shape
            w = torch.randn((cin, cout, kh, kw), generator=g) * 0.05
        else:
            cin, cout, kh, kw = shape
            w = torch.randn((cout, cin, kh, kw), generator=g) * 0.05
        sd[f"{name}.weight"] = w
        sd[f"{name}.bias"] = torch.randn(cout, generator=g) * 0.05
    return sd


def _pwc_corr(f1, f2):
    """81-channel channel-mean cost volume, k = (dy+4)*9 + (dx+4) (correlation.py)."""
    B, C, H, W = f1.shape
    f2p = F.pad(f2, (4, 4, 4, 4))
    out = []
    for dy in range(-4, 5):
        for dx in range(-4, 5):
            shifted = f2p[:, :, 4 + dy : 4 + dy + H, 4 + dx : 4 + dx + W]
            out.append((f1 * shifted).mean(1))
    return torch.stack(out, 1)


def _pwc_warp(x, flow):
    """Backward warp with ones-mask thresholding (pwc_net.py:23-41)."""
    B, C, H, W = x.shape
    gx = torch.linspace(-1, 1, W).view(1, 1, 1, W).expand(B, 1, H, W)
    gy = torch.linspace(-1, 1, H).view(1, 1, H, 1).expand(B, 1, H, W)
    grid = torch.cat([gx, gy], 1)
    nflow = torch.cat([flow[:, :1] / ((W - 1) / 2), flow[:, 1:] / ((H - 1) / 2)], 1)
    xm = torch.cat([x, torch.ones(B, 1, H, W)], 1)
    out = F.grid_sample(xm, (grid + nflow).permute(0, 2, 3, 1),
                        mode="bilinear", padding_mode="zeros", align_corners=True)
    mask = out[:, -1:]
    mask = (mask > 0.999).float()
    return out[:, :-1] * mask


def _pwc_pyramid(sd, x):
    feats = []
    for name in ("moduleOne", "moduleTwo", "moduleThr", "moduleFou", "moduleFiv", "moduleSix"):
        p = f"moduleExtractor.{name}"
        x = F.leaky_relu(F.conv2d(x, sd[f"{p}.0.weight"], sd[f"{p}.0.bias"], 2, 1), 0.1)
        x = F.leaky_relu(F.conv2d(x, sd[f"{p}.2.weight"], sd[f"{p}.2.bias"], 1, 1), 0.1)
        x = F.leaky_relu(F.conv2d(x, sd[f"{p}.4.weight"], sd[f"{p}.4.bias"], 1, 1), 0.1)
        feats.append(x)
    return feats


def pwc_torch_forward(sd, image1, image2):
    """(B, 3, H, W) float RGB [0,255] → (B, 2, H, W) flow (pwc_net.py:226-263)."""
    import math

    with torch.no_grad():
        B, C, H, W = image1.shape
        x1 = image1[:, [2, 1, 0]] / 255.0
        x2 = image2[:, [2, 1, 0]] / 255.0
        H64 = int(math.floor(math.ceil(H / 64.0) * 64.0))
        W64 = int(math.floor(math.ceil(W / 64.0) * 64.0))
        if (H64, W64) != (H, W):
            x1 = F.interpolate(x1, size=(H64, W64), mode="bilinear", align_corners=False)
            x2 = F.interpolate(x2, size=(H64, W64), mode="bilinear", align_corners=False)

        pyr1 = _pwc_pyramid(sd, x1)
        pyr2 = _pwc_pyramid(sd, x2)

        est = None
        for level in (6, 5, 4, 3, 2):
            mod = LEVEL_NAMES[level]
            f1, f2 = pyr1[level - 1], pyr2[level - 1]
            if est is None:
                feat = F.leaky_relu(_pwc_corr(f1, f2), 0.1)
            else:
                flow = F.conv_transpose2d(est["flow"], sd[f"{mod}.moduleUpflow.weight"],
                                          sd[f"{mod}.moduleUpflow.bias"], 2, 1)
                upfeat = F.conv_transpose2d(est["feat"], sd[f"{mod}.moduleUpfeat.weight"],
                                            sd[f"{mod}.moduleUpfeat.bias"], 2, 1)
                warped = _pwc_warp(f2, flow * DEC_BACKWARD[level])
                vol = F.leaky_relu(_pwc_corr(f1, warped), 0.1)
                feat = torch.cat([vol, f1, flow, upfeat], 1)
            for name in ("moduleOne", "moduleTwo", "moduleThr", "moduleFou", "moduleFiv"):
                y = F.leaky_relu(F.conv2d(feat, sd[f"{mod}.{name}.0.weight"],
                                          sd[f"{mod}.{name}.0.bias"], 1, 1), 0.1)
                feat = torch.cat([y, feat], 1)
            flow = F.conv2d(feat, sd[f"{mod}.moduleSix.0.weight"], sd[f"{mod}.moduleSix.0.bias"], 1, 1)
            est = {"flow": flow, "feat": feat}

        x = est["feat"]
        for idx, d in zip(("0", "2", "4", "6", "8", "10"), (1, 2, 4, 8, 16, 1)):
            p = f"moduleRefiner.moduleMain.{idx}"
            x = F.leaky_relu(F.conv2d(x, sd[f"{p}.weight"], sd[f"{p}.bias"], 1, d, d), 0.1)
        refined = F.conv2d(x, sd["moduleRefiner.moduleMain.12.weight"],
                           sd["moduleRefiner.moduleMain.12.bias"], 1, 1)

        temp = est["flow"] + refined
        flow = 20.0 * F.interpolate(temp, size=(H, W), mode="bilinear", align_corners=False)
        flow[:, 0] *= float(W) / float(W64)
        flow[:, 1] *= float(H) / float(H64)
        return flow


# ---------------------------------------------------------------------------
# R(2+1)D-18: functional torch mirror (torchvision r2plus1d_18 numerics). The
# shape table is transcribed INDEPENDENTLY from torchvision's VideoResNet
# (torchvision/models/video/resnet.py: Conv2Plus1D + BasicBlock + R2Plus1dStem;
# the checkpoint the reference loads at extract_r21d.py:57) — NOT imported from
# video_features_tpu.models.r21d.
# ---------------------------------------------------------------------------


def r21d_conv_shapes():
    """name → torch-layout shapes: conv (O, I, kt, kh, kw), ('bn', C), fc (O, I).

    torchvision computes midplanes ONCE per BasicBlock from (inplanes, planes)
    and reuses it for conv1 and conv2 — so downsampling blocks have a conv2
    midplanes smaller than midplanes(planes, planes) would give (e.g.
    layer2.0.conv2.0.0 is 230-wide, not 288).
    """
    shapes = {
        # R2Plus1dStem: (1,7,7)/(1,2,2) conv → BN → ReLU → (3,1,1) conv → BN → ReLU
        "stem.0": (45, 3, 1, 7, 7), "stem.1": ("bn", 45),
        "stem.3": (64, 45, 3, 1, 1), "stem.4": ("bn", 64),
    }
    cin = 64
    for stage, cout in enumerate((64, 128, 256, 512), start=1):
        for blk in range(2):
            p = f"layer{stage}.{blk}"
            block_in = cin if blk == 0 else cout
            mid = (block_in * cout * 3 * 3 * 3) // (block_in * 3 * 3 + 3 * cout)
            shapes[f"{p}.conv1.0.0"] = (mid, block_in, 1, 3, 3)
            shapes[f"{p}.conv1.0.1"] = ("bn", mid)
            shapes[f"{p}.conv1.0.3"] = (cout, mid, 3, 1, 1)
            shapes[f"{p}.conv1.1"] = ("bn", cout)
            shapes[f"{p}.conv2.0.0"] = (mid, cout, 1, 3, 3)
            shapes[f"{p}.conv2.0.1"] = ("bn", mid)
            shapes[f"{p}.conv2.0.3"] = (cout, mid, 3, 1, 1)
            shapes[f"{p}.conv2.1"] = ("bn", cout)
            if blk == 0 and stage > 1:
                shapes[f"{p}.downsample.0"] = (cout, block_in, 1, 1, 1)
                shapes[f"{p}.downsample.1"] = ("bn", cout)
        cin = cout
    shapes["fc"] = (400, 512)
    return shapes


def r21d_random_state_dict(seed: int = 0):
    g = torch.Generator().manual_seed(seed)
    sd = {}
    for name, shape in r21d_conv_shapes().items():
        if shape[0] == "bn":
            c = shape[1]
            sd[f"{name}.weight"] = torch.rand(c, generator=g) + 0.5
            sd[f"{name}.bias"] = torch.randn(c, generator=g) * 0.05
            sd[f"{name}.running_mean"] = torch.randn(c, generator=g) * 0.05
            sd[f"{name}.running_var"] = torch.rand(c, generator=g) + 0.5
        elif name == "fc":
            sd["fc.weight"] = torch.randn(shape, generator=g) * 0.05
            sd["fc.bias"] = torch.randn(shape[0], generator=g) * 0.05
        else:
            sd[f"{name}.weight"] = torch.randn(shape, generator=g) * 0.05
    return sd


def _r21d_bn(sd, name, x):
    return F.batch_norm(x, sd[f"{name}.running_mean"], sd[f"{name}.running_var"],
                        sd[f"{name}.weight"], sd[f"{name}.bias"], training=False)


def _r21d_2plus1(sd, prefix, x, stride=1):
    x = F.conv3d(x, sd[f"{prefix}.0.weight"], None, (1, stride, stride), (0, 1, 1))
    x = F.relu(_r21d_bn(sd, f"{prefix}.1", x))
    return F.conv3d(x, sd[f"{prefix}.3.weight"], None, (stride, 1, 1), (1, 0, 0))


def r21d_forward(sd, x, features=True):
    """(B, 3, T, H, W) normalized float → (B, 512) features or (B, 400) logits."""
    with torch.no_grad():
        x = F.conv3d(x, sd["stem.0.weight"], None, (1, 2, 2), (0, 3, 3))
        x = F.relu(_r21d_bn(sd, "stem.1", x))
        x = F.conv3d(x, sd["stem.3.weight"], None, 1, (1, 0, 0))
        x = F.relu(_r21d_bn(sd, "stem.4", x))
        for stage in range(1, 5):
            for blk in range(2):
                p = f"layer{stage}.{blk}"
                stride = 2 if (stage > 1 and blk == 0) else 1
                y = F.relu(_r21d_bn(sd, f"{p}.conv1.1", _r21d_2plus1(sd, f"{p}.conv1.0", x, stride)))
                y = _r21d_bn(sd, f"{p}.conv2.1", _r21d_2plus1(sd, f"{p}.conv2.0", y))
                if f"{p}.downsample.0.weight" in sd:
                    x = _r21d_bn(sd, f"{p}.downsample.1",
                                 F.conv3d(x, sd[f"{p}.downsample.0.weight"], None, (stride,) * 3))
                x = F.relu(x + y)
        x = x.mean((2, 3, 4))
        if features:
            return x
        return F.linear(x, sd["fc.weight"], sd["fc.bias"])


def random_init_(model: nn.Module, seed: int = 0) -> nn.Module:
    """Randomize all parameters and BN running stats so parity tests are non-trivial."""
    g = torch.Generator().manual_seed(seed)
    state = model.state_dict()
    for name, t in state.items():
        if t.dtype.is_floating_point:
            if name.endswith("running_var"):
                t.copy_(torch.rand(t.shape, generator=g) + 0.5)
            else:
                t.copy_(torch.randn(t.shape, generator=g) * 0.05)
    model.load_state_dict(state)
    model.eval()
    return model
