"""Turnkey real-weights parity runbook (VERDICT r3, Missing #2).

The reference's output semantics come entirely from its pretrained
checkpoints (``/root/reference/models/i3d/extract_i3d.py:21-24``,
``extract_raft.py:18``, ``extract_pwc.py:17``, torchvision
``pretrained=True``). Those blobs cannot be downloaded in this environment,
so this tool is the one command a user WITH the checkpoints runs to prove
the framework reproduces them:

    python tools/verify_parity.py --checkpoints_dir /path/to/ckpts

For every model whose checkpoint file is found it:
  1. converts the torch/TF weights through the production converters
     (``weights/convert_torch.py`` — the same code ``resolve_params`` uses);
  2. loads the SAME state dict into the independently-transcribed torch
     mirror (``tools/torch_mirrors.py``) and compares forwards on fixed
     random inputs — per-layer for I3D/RAFT (first divergence localized via
     ``tools/layer_diff.py``), end-to-end for the rest;
  3. writes a PASS/FAIL report (``--report`` JSON + a console table).

Missing checkpoints are reported as SKIPPED with the exact filename(s) to
supply; nothing found ⇒ the full shopping list is printed (same names
``tools/export_weights.py`` documents). ``--self_test`` runs the identical
code path on the deterministic seeded mirror state dicts (no blobs needed)
— that mode runs in CI (tests/test_verify_parity.py), so the runbook itself
cannot rot.

Exit code: 1 if any comparison FAILED, else 0.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# fp32 parity must not run through TPU bf16 matmul passes (see layer_diff.py)
os.environ["JAX_PLATFORMS"] = "cpu"
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

# checkpoint filenames the reference ecosystem ships, per model
EXPECTED_FILES = {
    "i3d_rgb": ("i3d_rgb.pt", "rgb.pt", "rgb_imagenet.pt"),
    "i3d_flow": ("i3d_flow.pt", "flow.pt", "flow_imagenet.pt"),
    "raft-sintel": ("raft-sintel.pth", "raft-sintel.pt", "raft-things.pth"),
    "pwc-sintel": ("network-default.pytorch", "pwc-sintel.pth", "pwc_net.pth"),
    "r2plus1d_18": ("r2plus1d_18-91a641e6.pth", "r2plus1d_18.pth"),
    "resnet50": ("resnet50-0676ba61.pth", "resnet50.pth"),
    "vggish": ("vggish_tf_vars.npz", "vggish_model.ckpt"),
}

# relative-error budget: fp32 re-implementation vs torch on CPU; layer_diff's
# DIVERGES threshold uses the same figure
REL_BUDGET = 1e-3


def _rel_err(ours: np.ndarray, ref: np.ndarray) -> float:
    scale = max(float(np.max(np.abs(ref))), 1e-9)
    return float(np.max(np.abs(ours - ref))) / scale


def _find(ckpt_dir, model):
    for fname in EXPECTED_FILES[model]:
        path = os.path.join(ckpt_dir, fname)
        if os.path.exists(path):
            return path
    return None


def _load_sd(path):
    import torch

    sd = torch.load(path, map_location="cpu", weights_only=True)
    if isinstance(sd, dict) and "state_dict" in sd:
        sd = sd["state_dict"]
    # raft checkpoints ship with DataParallel 'module.' prefixes
    return { (k[7:] if k.startswith("module.") else k): v for k, v in sd.items() }


def verify_i3d(modality, sd):
    from tools.layer_diff import i3d_layer_diff

    rows = i3d_layer_diff(modality, sd=sd)
    worst = max((d / max(s, 1.0) for _n, d, s in rows), default=0.0)
    first_bad = next((n for n, d, s in rows if d > REL_BUDGET * max(s, 1.0)), None)
    return worst, {"stages": len(rows), "first_divergence": first_bad}


def verify_raft(sd):
    from tools.layer_diff import raft_layer_diff

    rows = raft_layer_diff(iters=4, sd=sd)
    worst = max((d / max(s, 1.0) for _n, d, s in rows), default=0.0)
    first_bad = next((n for n, d, s in rows if d > REL_BUDGET * max(s, 1.0)), None)
    return worst, {"stages": len(rows), "first_divergence": first_bad}


def verify_pwc(sd):
    import torch

    from tools.torch_mirrors import pwc_torch_forward

    from video_features_tpu.models.pwc import pwc_forward
    from video_features_tpu.weights.convert_torch import convert_pwc

    rng = np.random.default_rng(0)
    im1 = rng.uniform(0, 255, (1, 128, 128, 3)).astype(np.float32)
    im2 = rng.uniform(0, 255, (1, 128, 128, 3)).astype(np.float32)
    ref = pwc_torch_forward(
        sd, torch.from_numpy(np.moveaxis(im1, -1, 1)),
        torch.from_numpy(np.moveaxis(im2, -1, 1))).numpy()
    ours = np.moveaxis(np.asarray(pwc_forward(convert_pwc(sd), im1, im2)), -1, 1)
    return _rel_err(ours, ref), {"shape": list(ref.shape)}


def verify_r21d(sd):
    import torch

    from tools.torch_mirrors import r21d_forward

    from video_features_tpu.models.r21d import R2Plus1D18
    from video_features_tpu.weights.convert_torch import convert_r21d

    rng = np.random.default_rng(0)
    x = rng.normal(size=(1, 8, 64, 64, 3)).astype(np.float32)  # normalized-ish
    ref = r21d_forward(sd, torch.from_numpy(
        np.transpose(x, (0, 4, 1, 2, 3))), features=True).numpy()
    model = R2Plus1D18()
    ours = np.asarray(model.apply(
        {"params": convert_r21d(sd)}, x, features=True))
    return _rel_err(ours, ref), {"shape": list(ref.shape)}


def verify_resnet50(sd):
    import torch

    from tools.torch_mirrors import ResNet50 as TorchResNet50

    from video_features_tpu.models.resnet import ResNet50
    from video_features_tpu.weights.convert_torch import convert_resnet50

    rng = np.random.default_rng(0)
    x = rng.normal(size=(2, 64, 64, 3)).astype(np.float32)
    tm = TorchResNet50()
    tm.load_state_dict({k: torch.as_tensor(np.asarray(v)) for k, v in sd.items()
                        if "num_batches_tracked" not in k}, strict=False)
    tm.eval()
    with torch.no_grad():
        ref = tm(torch.from_numpy(np.transpose(x, (0, 3, 1, 2))),
                 features=True).numpy()
    model = ResNet50()
    ours = np.asarray(model.apply(
        {"params": convert_resnet50(sd)}, x, features=True))
    return _rel_err(ours, ref), {"shape": list(ref.shape)}


def verify_vggish(path):
    """No torch mirror exists for the TF-slim VGGish; verify convert + finite
    forward at the documented embedding shape (full numeric parity for VGGish
    is pinned by tests/test_vggish.py against the published DSP spec)."""
    from video_features_tpu.models.vggish import VGGish, convert_tf_vggish

    if path.endswith(".ckpt"):
        try:
            import tensorflow as tf  # noqa: F401
        except ImportError:
            return None, {"note": "needs tensorflow to read .ckpt; export "
                                  "vggish_tf_vars.npz instead (see "
                                  "tools/export_weights.py)"}
        from tools.export_weights import load_tf_ckpt  # type: ignore

        flat = load_tf_ckpt(path)
    else:
        with np.load(path) as z:
            flat = {k: z[k] for k in z.files}
    params = convert_tf_vggish(flat)
    x = np.zeros((2, 96, 64), np.float32)
    out = np.asarray(VGGish().apply({"params": params}, x))
    ok = out.shape == (2, 128) and bool(np.isfinite(out).all())
    return (0.0 if ok else float("inf")), {"shape": list(out.shape)}


def self_test_sds():
    """Deterministic seeded mirror state dicts — the CI path."""
    import torch

    from tools.torch_mirrors import (
        ResNet50 as TorchResNet50,
        i3d_random_state_dict,
        pwc_random_state_dict,
        r21d_random_state_dict,
        raft_random_state_dict,
        random_init_,
    )

    resnet_sd = random_init_(TorchResNet50(), seed=0).state_dict()
    return {
        "i3d_rgb": i3d_random_state_dict("rgb", seed=0),
        "i3d_flow": i3d_random_state_dict("flow", seed=0),
        "raft-sintel": raft_random_state_dict(seed=0),
        "pwc-sintel": pwc_random_state_dict(seed=0),
        "r2plus1d_18": r21d_random_state_dict(seed=0),
        "resnet50": {k: v for k, v in resnet_sd.items()},
    }


VERIFIERS = {
    "i3d_rgb": lambda sd: verify_i3d("rgb", sd),
    "i3d_flow": lambda sd: verify_i3d("flow", sd),
    "raft-sintel": verify_raft,
    "pwc-sintel": verify_pwc,
    "r2plus1d_18": verify_r21d,
    "resnet50": verify_resnet50,
}


def run(ckpt_dir=None, self_test=False, models=None, report_path=None) -> int:
    results = {}
    sds = self_test_sds() if self_test else None
    names = models or list(EXPECTED_FILES)
    for model in names:
        entry = {"model": model}
        try:
            if self_test:
                if model == "vggish":
                    continue  # TF-side model: no torch mirror to self-test
                worst, extra = VERIFIERS[model](sds[model])
                entry["source"] = "self_test(seeded mirror weights)"
            else:
                path = _find(ckpt_dir, model)
                if path is None:
                    entry.update(status="SKIPPED",
                                 supply_one_of=list(EXPECTED_FILES[model]))
                    results[model] = entry
                    continue
                entry["source"] = path
                if model == "vggish":
                    worst, extra = verify_vggish(path)
                    if worst is None:
                        entry.update(status="SKIPPED", **extra)
                        results[model] = entry
                        continue
                else:
                    worst, extra = VERIFIERS[model](_load_sd(path))
            entry.update(extra)
            entry["worst_rel_err"] = worst
            entry["status"] = "PASS" if worst <= REL_BUDGET else "FAIL"
        except Exception as e:  # noqa: BLE001 — per-model fault barrier
            entry.update(status="ERROR", error=f"{type(e).__name__}: {e}"[:300])
        results[model] = entry

    print(f"\n{'model':<14} {'status':<8} {'worst rel err':>14}  source")
    for model, e in results.items():
        err = e.get("worst_rel_err")
        err_s = f"{err:.3e}" if isinstance(err, float) else "-"
        src = e.get("source") or ", ".join(e.get("supply_one_of", []))
        print(f"{model:<14} {e['status']:<8} {err_s:>14}  {src}")
        if e.get("first_divergence"):
            print(f"{'':<14} first diverging stage: {e['first_divergence']}")
    n_skip = sum(e["status"] == "SKIPPED" for e in results.values())
    if n_skip == len(results):
        print("\nNo checkpoints found. Supply any of the files above in "
              "--checkpoints_dir (see tools/export_weights.py for where each "
              "comes from), then re-run. docs/parity.md is the full runbook.")

    if report_path:
        with open(report_path, "w") as f:
            json.dump(results, f, indent=2)
        print(f"\nreport written to {report_path}")
    return 1 if any(e["status"] in ("FAIL", "ERROR") for e in results.values()) else 0


def main():
    ap = argparse.ArgumentParser(
        description="Verify converted reference checkpoints against the torch "
                    "mirrors (see module docstring)")
    ap.add_argument("--checkpoints_dir", default="./checkpoints",
                    help="directory holding the reference checkpoint files")
    ap.add_argument("--self_test", action="store_true",
                    help="run the identical pipeline on seeded mirror weights "
                         "(no checkpoint files needed; the CI mode)")
    ap.add_argument("--models", nargs="*", default=None,
                    help=f"subset of {list(EXPECTED_FILES)}")
    ap.add_argument("--report", default=None, help="write a JSON report here")
    args = ap.parse_args()
    sys.exit(run(args.checkpoints_dir, args.self_test, args.models, args.report))


if __name__ == "__main__":
    main()
