# Makes `tools` a regular package so `python -m tools.vftlint` and
# `from tools.vftlint import ...` resolve without namespace-package ambiguity.
# The standalone scripts in this directory keep working unchanged.
