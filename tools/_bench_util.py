"""Shared timing methodology for the stage profilers (profile_raft/profile_i3d).

The axon tunnel backend memoizes identical (executable, args) calls and returns
from ``block_until_ready`` without waiting, so honest timing needs (a) unique
input arrays per call and (b) a forced host read that data-depends on every
output leaf; the per-round host-sync latency is measured and subtracted
(bench.py documents the full methodology).
"""

from __future__ import annotations

import os
import statistics
import time


def enable_compilation_cache():
    """Tunnel compiles dominate wall time; reuse bench.py's persistent cache."""
    import jax

    try:
        jax.config.update("jax_compilation_cache_dir",
                          os.environ.get("JAX_COMPILATION_CACHE_DIR", "/tmp/jax_cache"))
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
    except Exception:
        pass


def force(outs) -> float:
    """Force execution of every output with ONE host fetch (see bench.py)."""
    import jax
    import jax.numpy as jnp

    leaves = [l for l in jax.tree_util.tree_leaves(outs)
              if l is not None and getattr(l, "size", 1)]
    acc = None
    for l in leaves:
        v = l.ravel()[0].astype(jnp.float32)
        acc = v if acc is None else acc + v
    return float(acc)


def timeit(fn) -> float:
    t0 = time.perf_counter()
    fn()
    return time.perf_counter() - t0


def time_fn(name, fn, mk_inputs, iters=4, repeats=3):
    """Median seconds/iteration with unique inputs per call; prints one line."""
    warm = fn(*mk_inputs())
    force(warm)  # compile + first execution
    sync = statistics.median([timeit(lambda: force(warm)) for _ in range(3)])
    times = []
    for _ in range(repeats):
        ins = [mk_inputs() for _ in range(iters)]
        force(ins)  # input transfers completed pre-clock
        t0 = time.perf_counter()
        outs = [fn(*ins[i]) for i in range(iters)]
        force(outs)
        times.append(max(time.perf_counter() - t0 - sync, 1e-9) / iters)
    med = statistics.median(times)
    print(f"{name:>16}: {med * 1e3:9.2f} ms/iter  (sync {sync * 1e3:.0f} ms)",
          flush=True)
    return med
