"""Shared timing for the stage profilers (profile_raft / profile_i3d).

The methodology of record lives in bench.py (unique inputs per call to defeat
the axon tunnel's result memoization, one forced host read that data-depends on
every output leaf, sync-latency subtraction, iteration auto-raise against the
noise floor); this module re-exports it so the profilers and the bench can
never drift apart.
"""

from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import bench as _bench  # noqa: E402 — repo-root bench.py

force = _bench._force
timeit = _bench._timeit


def enable_compilation_cache():
    """Tunnel compiles dominate wall time; reuse bench.py's persistent cache.

    Also honors an explicit JAX_PLATFORMS (e.g. a cpu sanity run) through the
    config API — the image's sitecustomize pins the axon platform, so the env
    var alone would still dial the TPU tunnel (and hang for ~50 min when the
    tunnel is down)."""
    import jax

    want = os.environ.get("JAX_PLATFORMS")
    if want:
        try:
            jax.config.update("jax_platforms", want)
        except Exception:
            pass
    try:
        jax.config.update("jax_compilation_cache_dir",
                          os.environ.get("JAX_COMPILATION_CACHE_DIR", "/tmp/jax_cache"))
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
    except Exception:
        pass


def time_fn(name, fn, mk_inputs, iters=4, repeats=3):
    """Median seconds/iteration via bench._time_step (auto-raised iterations);
    prints one line, flagging measurements still under 3× the sync latency."""
    sec, sync, iters_run = _bench._time_step(fn, mk_inputs, iters, repeats)
    flag = "  [noise-limited]" if iters_run * sec < 3 * sync else ""
    print(f"{name:>16}: {sec * 1e3:9.2f} ms/iter  "
          f"(sync {sync * 1e3:.0f} ms, iters {iters_run}){flag}", flush=True)
    return sec
