"""Extract features from videos — TPU-native CLI (reference-compatible entry).

Drop-in surface of the reference ``main.py`` (same flags), e.g.::

    python main.py --feature_type i3d --video_paths a.mp4 b.mp4 --on_extraction save_numpy

The implementation lives in :mod:`video_features_tpu.run`; ``pip install -e .``
also exposes it as the ``video-features-tpu`` console script.
"""

import sys

from video_features_tpu.run import main

if __name__ == "__main__":
    sys.exit(main())
