"""Extract features from videos — TPU-native CLI.

Drop-in surface of the reference ``main.py`` (same flags), e.g.::

    python main.py --feature_type i3d --video_paths a.mp4 b.mp4 --on_extraction save_numpy

Videos are embarrassingly parallel: the list is processed by the extractor, whose
device step is jit-compiled for the local TPU mesh; multi-host jobs shard the list
round-robin per host (``--num_devices`` governs the local mesh size).
"""

import sys

from video_features_tpu.cli import parse_args
from video_features_tpu.extractors import get_extractor


def main(argv=None) -> int:
    cfg = parse_args(argv)
    extractor = get_extractor(cfg)
    paths = extractor.video_list()
    if not paths:
        print("No videos to process.")
        return 1

    def progress(done, total):
        print(f"\r[{done}/{total}] videos processed", end="", flush=True)

    ok = extractor.run(paths, progress=progress)
    print()
    failed = len(paths) - ok
    if failed:
        print(f"{failed} video(s) failed (see log above)")
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
